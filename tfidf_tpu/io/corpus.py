"""Corpus discovery, loading, and static-shape packing.

Reference contract (SURVEY §2 C1-C2): rank 0 counts the entries of
``./input`` via ``opendir``/``readdir`` skipping ``.``/``..``
(``TFIDF.c:98-110``); documents are 1-indexed and named exactly
``doc1..docN`` (``TFIDF.c:132-133``); a missing file is a hard error
(``TFIDF.c:137``). :func:`discover_corpus` honours that contract, plus a
``strict=False`` mode that accepts any directory of files (sorted by
name) since real corpora are not named ``doc<i>``.

Packing: TPU kernels need static shapes, so documents are tokenized,
hashed to ids, and packed into a padded ``[D, L]`` int32 batch with a
``lengths`` vector — the moral replacement for the reference's
token-at-a-time ``fscanf`` streaming (``TFIDF.c:147``). ``D`` can be
padded up to a mesh-divisible count with empty docs (length 0), which the
masked histogram ignores by construction.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from tfidf_tpu.config import PipelineConfig, TokenizerKind, VocabMode
from tfidf_tpu.io import fast_tokenizer
from tfidf_tpu.ops.hashing import words_to_ids
from tfidf_tpu.ops.tokenize import char_ngrams, whitespace_tokenize


@dataclasses.dataclass
class Corpus:
    """Raw documents: parallel lists of names and byte contents."""

    names: List[str]
    docs: List[bytes]

    def __len__(self) -> int:
        return len(self.docs)


@dataclasses.dataclass
class PackedBatch:
    """Static-shape device input.

    token_ids: [D, L] vocab ids, padded past each doc's length. int32,
      or uint16 when packed by the native loader with vocab <= 2^16
      (half the host->device bytes); device ops normalize to int32 at
      their entry points (``ops.histogram.tf_counts_masked``,
      ``ops.sparse.sorted_term_counts``).
    lengths: int32 [D] live token counts (== the reference's ``docSize``,
      ``TFIDF.c:141-143``).
    num_docs: real document count (D may exceed it via mesh padding).
    names: D document names ('' for padding docs).
    vocab_size: V for this batch.
    id_to_word: optional id -> representative token bytes, for output
      formatting. EXACT mode: the true inverse vocabulary. HASHED mode:
      first-seen token per bucket (collisions share a representative).
    """

    token_ids: np.ndarray
    lengths: np.ndarray
    num_docs: int
    names: List[str]
    vocab_size: int
    id_to_word: Optional[Dict[int, bytes]]


@dataclasses.dataclass
class RaggedBatch:
    """Ragged (CSR-style) device input — the minibatch twin of the
    overlapped ingest's flat chunk wire (``ingest.flatten_aligned``).

    Instead of a padded ``[D, L]`` batch, documents ship as ONE
    concatenated id stream with each doc starting at a multiple of
    ``align`` ids (zero fill between docs, bucket-padded tail), so
    host→device bytes scale with real tokens instead of ``D×L``. The
    padded batch is rebuilt on device (``ingest.rebuild_padded``) —
    or on host (:func:`ragged_to_padded_host`) for consumers whose
    wire must stay padded (mesh paths, by doctrine).

    flat: [N] uint16/int32 granule-aligned flat id stream (N a
      ``_FLAT_BUCKET`` multiple — the ingest wire contract).
    lengths: int32 [D] live token counts.
    length: static L of the rebuilt batch.
    align: wire granule (every doc's ids start at a multiple of it).
    total: live (pre-bucket-pad) aligned id count.
    """

    flat: np.ndarray
    lengths: np.ndarray
    length: int
    align: int
    total: int
    num_docs: int
    names: List[str]
    vocab_size: int
    id_to_word: Optional[Dict[int, bytes]]

    def to_padded(self) -> PackedBatch:
        """Host-side rebuild into the equivalent :class:`PackedBatch`
        (bit-identical to the padded packer's zero-padded layout)."""
        return PackedBatch(
            token_ids=ragged_to_padded_host(self.flat, self.lengths,
                                            self.length, self.align),
            lengths=self.lengths, num_docs=self.num_docs,
            names=self.names, vocab_size=self.vocab_size,
            id_to_word=self.id_to_word)


def ragged_to_padded_host(flat: np.ndarray, lengths: np.ndarray,
                          length: int, align: int = 1) -> np.ndarray:
    """Numpy inverse of ``ingest.flatten_aligned``: rebuild the padded
    ``[D, L]`` int32 batch from a flat aligned id stream. Padding slots
    are zero-filled (the padded packers' layout), unlike the device
    rebuild's clamp-and-mask contract — so this one is bit-identical
    to ``pack_corpus`` output and serves the mesh (padded-wire) paths
    and round-trip tests."""
    lens = np.maximum(lengths.astype(np.int64), 0)
    per_doc = -(-lens // align) * align
    off = np.concatenate([[0], np.cumsum(per_doc)[:-1]])
    idx = np.minimum(off[:, None] + np.arange(length)[None, :],
                     max(flat.size - 1, 0))
    out = flat[idx].astype(np.int32)
    return np.where(np.arange(length)[None, :] < lens[:, None], out, 0)


def ragged_from_packed(batch: PackedBatch,
                       align: Optional[int] = None) -> RaggedBatch:
    """Flatten a :class:`PackedBatch` into the ragged wire format via
    ``ingest.flatten_aligned`` (the single Python definition of the
    wire layout), uint16 ids for vocabs within 2^16 and int32 beyond —
    the same width rule the native packers apply. ``align`` defaults
    to the run's wire granule (``TFIDF_TPU_WIRE_ALIGN``)."""
    # Lazy import: ingest imports this module at load time.
    from tfidf_tpu.ingest import _wire_align, flatten_aligned
    if align is None:
        align = _wire_align()
    dtype = np.uint16 if batch.vocab_size <= (1 << 16) else np.int32
    flat, total = flatten_aligned(batch.token_ids, batch.lengths, align,
                                  dtype=dtype)
    return RaggedBatch(flat=flat, lengths=batch.lengths,
                       length=batch.token_ids.shape[1], align=align,
                       total=total, num_docs=batch.num_docs,
                       names=batch.names, vocab_size=batch.vocab_size,
                       id_to_word=batch.id_to_word)


def pack_ragged(corpus: Corpus, config: PipelineConfig,
                pad_docs_to: Optional[int] = None,
                want_words: bool = True,
                align: Optional[int] = None) -> RaggedBatch:
    """Tokenize + id-map into the ragged wire format.

    Same tokenize/hash front end as :func:`pack_corpus` (one code
    path — the padded batch is built first, then flattened), so a
    :class:`RaggedBatch` and a :class:`PackedBatch` of the same corpus
    are always rebuild-equal."""
    return ragged_from_packed(
        pack_corpus(corpus, config, pad_docs_to=pad_docs_to,
                    want_words=want_words), align)


@dataclasses.dataclass
class PackedBytes:
    """Raw-byte device input for the on-device chargram path.

    byte_ids: int32 [D, B] raw bytes (0..255), zero-padded.
    byte_lengths: int32 [D] live byte counts.
    """

    byte_ids: np.ndarray
    byte_lengths: np.ndarray
    num_docs: int
    names: List[str]


def pack_bytes(corpus: Corpus, pad_docs_to: Optional[int] = None,
               pad_len_to: int = 128) -> PackedBytes:
    """Pack raw document bytes for on-device n-gram hashing."""
    d = len(corpus)
    d_padded = max(pad_docs_to or d, d)
    max_len = max((len(doc) for doc in corpus.docs), default=1)
    b = max(((max_len + pad_len_to - 1) // pad_len_to) * pad_len_to, pad_len_to)
    byte_ids = np.zeros((d_padded, b), dtype=np.int32)
    lengths = np.zeros((d_padded,), dtype=np.int32)
    for i, doc in enumerate(corpus.docs):
        byte_ids[i, : len(doc)] = np.frombuffer(doc, np.uint8)
        lengths[i] = len(doc)
    names = list(corpus.names) + [""] * (d_padded - d)
    return PackedBytes(byte_ids=byte_ids, byte_lengths=lengths,
                       num_docs=d, names=names)


def discover_names(input_dir: str, strict: bool = True) -> List[str]:
    """The reference's corpus-discovery contract, names only.

    strict=True: count *every* directory entry except ``.``/``..``
    (subdirectories included — the reference's readdir loop skips only
    those two names, ``TFIDF.c:104-109``), then *derive* the names
    ``doc1..docN`` (``TFIDF.c:132-133`` — the reference never reads the
    listing's names, only its count). strict=False: every regular file,
    sorted by name. Single source of truth for :func:`discover_corpus`,
    :func:`load_and_pack`, and chunked ingest.
    """
    if strict:
        # os.listdir already omits '.' and '..', so the raw count is the
        # reference's numDocs — a stray subdir in input/ inflates it and
        # shifts IDF exactly as it would for the reference.
        return [f"doc{i}" for i in range(1, len(os.listdir(input_dir)) + 1)]
    return sorted(e for e in os.listdir(input_dir)
                  if os.path.isfile(os.path.join(input_dir, e)))


def discover_corpus(input_dir: str, strict: bool = True) -> Corpus:
    """Enumerate and load a document directory.

    Names per :func:`discover_names`; raises FileNotFoundError if a
    strict-mode ``doc<i>`` is missing, matching the reference's hard
    exit (``TFIDF.c:137``).
    """
    names = discover_names(input_dir, strict)
    docs = []
    for name in names:
        path = os.path.join(input_dir, name)
        with open(path, "rb") as f:  # raises like the reference's exit(2)
            docs.append(f.read())
    return Corpus(names=names, docs=docs)


def load_and_pack(input_dir: str, config: PipelineConfig,
                  strict: bool = True,
                  pad_docs_to: Optional[int] = None) -> PackedBatch:
    """Directory -> device-ready batch, bypassing Python per-doc loops.

    The big-corpus ingest path: for HASHED + WHITESPACE configs the
    native parallel loader (``native/loader.cc``) reads, tokenizes,
    hashes, and packs with a thread pool — document bytes never enter
    Python. Other configs fall back to :func:`discover_corpus` +
    :func:`pack_corpus` (identical output, pinned by tests).
    """
    native_ok = (
        config.vocab_mode is VocabMode.HASHED
        and config.tokenizer is TokenizerKind.WHITESPACE
        and fast_tokenizer.loader_available())
    if not native_ok:
        return pack_corpus(discover_corpus(input_dir, strict=strict), config,
                           pad_docs_to=pad_docs_to, want_words=False)

    names = discover_names(input_dir, strict)
    paths = [os.path.join(input_dir, n) for n in names]
    packed = fast_tokenizer.load_pack_paths(
        paths, config.vocab_size, config.hash_seed,
        config.truncate_tokens_at, min_len=config.max_doc_len,
        chunk=config.doc_chunk, pad_docs_to=pad_docs_to)
    assert packed is not None  # loader_available() checked above
    token_ids, lengths = packed
    return PackedBatch(
        token_ids=token_ids, lengths=lengths, num_docs=len(names),
        names=names + [""] * (token_ids.shape[0] - len(names)),
        vocab_size=config.vocab_size, id_to_word={})


def _tokens_for(doc: bytes, config: PipelineConfig) -> List[bytes]:
    if config.tokenizer is TokenizerKind.WHITESPACE:
        return whitespace_tokenize(doc, config.truncate_tokens_at)
    lo, hi = config.ngram_range
    return char_ngrams(doc, lo, hi)


def build_exact_vocab(token_docs: Sequence[Sequence[bytes]]) -> Dict[bytes, int]:
    """String -> id over the corpus, first-appearance order.

    The collision-free analog of the reference's string-keyed tables
    (``TFIDF.c:26-42``); id order is arbitrary because output is sorted
    lexicographically at emit time (``TFIDF.c:273``).
    """
    vocab: Dict[bytes, int] = {}
    for toks in token_docs:
        for t in toks:
            if t not in vocab:
                vocab[t] = len(vocab)
    return vocab


def pack_corpus(corpus: Corpus, config: PipelineConfig,
                pad_docs_to: Optional[int] = None,
                want_words: bool = True) -> PackedBatch:
    """Tokenize + id-map + pad into a device-ready batch.

    ``want_words=False`` skips building the id -> word map — the big-run
    mode where results are consumed by id (top-k recall, benchmarks) and
    the host should not hold per-token strings.

    HASHED + WHITESPACE uses the native one-pass tokenize+hash kernel
    (``native/fast_tokenizer.cc``) when built, falling back to the
    Python path transparently.
    """
    use_native_hash = (
        config.vocab_mode is VocabMode.HASHED
        and config.tokenizer is TokenizerKind.WHITESPACE
        and not want_words
        and fast_tokenizer.available())

    if use_native_hash:
        vocab_size = config.vocab_size
        id_docs = [fast_tokenizer.tokenize_hash_ids(
            doc, vocab_size, config.hash_seed, config.truncate_tokens_at)
            for doc in corpus.docs]
        lengths = np.array([len(i) for i in id_docs], dtype=np.int32)
        id_to_word: Dict[int, bytes] = {}
    else:
        token_docs = [_tokens_for(doc, config) for doc in corpus.docs]
        lengths = np.array([len(t) for t in token_docs], dtype=np.int32)

        if config.vocab_mode is VocabMode.EXACT:
            vocab = build_exact_vocab(token_docs)
            vocab_size = max(len(vocab), 1)
            id_docs = [np.array([vocab[t] for t in toks], dtype=np.int32)
                       for toks in token_docs]
            id_to_word = {i: w for w, i in vocab.items()} if want_words else {}
        else:
            vocab_size = config.vocab_size
            id_docs = []
            id_to_word = {}
            for toks in token_docs:
                ids = words_to_ids(toks, vocab_size, config.hash_seed)
                id_docs.append(ids)
                if want_words:
                    for t, i in zip(toks, ids):
                        id_to_word.setdefault(int(i), t)

    max_len = int(lengths.max(initial=0))
    chunk = config.doc_chunk
    # Static L: at least max_doc_len, grown to fit the longest doc, and
    # always a chunk multiple (tf_counts_chunked's precondition); long
    # docs then stream through the chunked scan.
    padded_len = max(config.max_doc_len, max_len, 1)
    padded_len = ((padded_len + chunk - 1) // chunk) * chunk

    d = len(corpus)
    d_padded = max(pad_docs_to or d, d)
    token_ids = np.zeros((d_padded, padded_len), dtype=np.int32)
    out_lengths = np.zeros((d_padded,), dtype=np.int32)
    for i, ids in enumerate(id_docs):
        token_ids[i, : len(ids)] = ids
        out_lengths[i] = len(ids)

    names = list(corpus.names) + [""] * (d_padded - d)
    return PackedBatch(
        token_ids=token_ids,
        lengths=out_lengths,
        num_docs=d,
        names=names,
        vocab_size=vocab_size,
        id_to_word=id_to_word,
    )
