"""ctypes bindings for the native tokenize+hash kernel.

``native/fast_tokenizer.cc`` implements the loader's host hot loop
(tokenize -> FNV-1a -> fold) in one C++ pass; this module exposes it to
Python and transparently falls back to the pure-Python implementation
when the shared library has not been built (``make -C native``).

The native and Python paths are contract-identical (pinned by
tests/test_native.py), so callers never need to know which ran.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "fast_tokenizer.so")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if os.environ.get("TFIDF_TPU_NO_NATIVE") or not os.path.exists(_LIB_PATH):
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        _load_failed = True
        return None
    lib.tok_count.restype = ctypes.c_int64
    lib.tok_count.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.tok_hash_ids.restype = ctypes.c_int64
    lib.tok_hash_ids.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.tok_spans.restype = ctypes.c_int64
    lib.tok_spans.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    _lib = lib
    return _lib


def available() -> bool:
    """True when the native library is loadable."""
    return _load() is not None


def tokenize_hash_ids(data: bytes, vocab_size: int, seed: int = 0,
                      truncate_at: Optional[int] = None) -> Optional[np.ndarray]:
    """Native tokenize+hash: doc bytes -> int32 vocab ids.

    Returns None when the native library is unavailable (caller falls
    back to the Python path).
    """
    lib = _load()
    if lib is None:
        return None
    n = lib.tok_count(data, len(data))
    out = np.empty(n, dtype=np.int32)
    wrote = lib.tok_hash_ids(
        data, len(data), ctypes.c_uint64(seed), vocab_size,
        truncate_at or 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    assert wrote == n, f"tokenizer wrote {wrote} of {n} tokens"
    return out


def tokenize_spans(data: bytes) -> Optional[List[bytes]]:
    """Native tokenization returning token byte-strings (EXACT mode)."""
    lib = _load()
    if lib is None:
        return None
    n = lib.tok_count(data, len(data))
    offs = np.empty(n, dtype=np.int64)
    lens = np.empty(n, dtype=np.int64)
    wrote = lib.tok_spans(
        data, len(data),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n)
    assert wrote == n
    return [data[o:o + l] for o, l in zip(offs.tolist(), lens.tolist())]
