"""ctypes bindings for the native tokenize+hash kernel.

``native/fast_tokenizer.cc`` implements the loader's host hot loop
(tokenize -> FNV-1a -> fold) in one C++ pass; this module exposes it to
Python and transparently falls back to the pure-Python implementation
when the shared library has not been built (``make -C native``).

The native and Python paths are contract-identical (pinned by
tests/test_native.py), so callers never need to know which ran.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "fast_tokenizer.so")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False
_has_loader = False
_has_open2 = False
_has_rerank = False
_has_flat = False
_has_flat_v2 = False
_has_flat_v3 = False
_has_slab = False
_has_intern = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed, _has_loader, _has_open2, _has_rerank, \
        _has_flat, _has_flat_v2, _has_flat_v3, _has_slab, _has_intern
    # The kill-switch wins even over an already-loaded library, and a
    # missing .so is not sticky (tests build it on demand mid-process).
    if os.environ.get("TFIDF_TPU_NO_NATIVE"):
        return None
    if _lib is not None or _load_failed:
        return _lib
    # TFIDF_TPU_NATIVE_LIB points at an alternate build of the same
    # library — how the sanitizer tests drive the ASan/UBSan .so
    # through the real ctypes bindings. Read at first load; the
    # resolved library then sticks for the process.
    lib_path = os.environ.get("TFIDF_TPU_NATIVE_LIB") or _LIB_PATH
    if not os.path.exists(lib_path):
        return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        _load_failed = True
        return None
    lib.tok_count.restype = ctypes.c_int64
    lib.tok_count.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.tok_hash_ids.restype = ctypes.c_int64
    lib.tok_hash_ids.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.tok_spans.restype = ctypes.c_int64
    lib.tok_spans.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    try:
        lib.loader_open.restype = ctypes.c_void_p
        lib.loader_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
        lib.loader_error.restype = ctypes.c_int64
        lib.loader_error.argtypes = [ctypes.c_void_p]
        lib.loader_token_count.restype = ctypes.c_int64
        lib.loader_token_count.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.loader_max_count.restype = ctypes.c_int64
        lib.loader_max_count.argtypes = [ctypes.c_void_p]
        lib.loader_fill.restype = None
        lib.loader_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        lib.loader_fill_u16.restype = None
        lib.loader_fill_u16.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint16), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        lib.loader_close.restype = None
        lib.loader_close.argtypes = [ctypes.c_void_p]
        _has_loader = True
    except AttributeError:  # stale .so predating the loader
        pass
    try:
        lib.loader_open2.restype = ctypes.c_void_p
        lib.loader_open2.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
        _has_open2 = True
    except AttributeError:  # stale .so predating open2
        pass
    try:
        lib.loader_fill_flat_u16.restype = ctypes.c_int64
        lib.loader_fill_flat_u16.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint16),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
        _has_flat = True
    except AttributeError:  # stale .so predating the flat packer
        pass
    try:
        lib.loader_fill_flat_u16_v2.restype = ctypes.c_int64
        lib.loader_fill_flat_u16_v2.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint16), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
        _has_flat_v2 = True
    except AttributeError:  # stale .so predating the capacity fill
        pass
    try:
        lib.loader_fill_flat_u16_v3.restype = ctypes.c_int64
        lib.loader_fill_flat_u16_v3.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint16), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int]
        _has_flat_v3 = True
    except AttributeError:  # stale .so predating the threaded fill
        pass
    try:
        lib.loader_slab_bytes.restype = ctypes.c_int64
        lib.loader_slab_bytes.argtypes = [ctypes.c_void_p,
                                          ctypes.c_int64]
        lib.loader_fill_slab.restype = ctypes.c_int64
        lib.loader_fill_slab.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int]
        _has_slab = True
    except AttributeError:  # stale .so predating the bytes wire
        pass
    try:
        lib.intern_open.restype = ctypes.c_void_p
        lib.intern_open.argtypes = [ctypes.c_int64]
        lib.intern_fill_flat_u16.restype = ctypes.c_int64
        lib.intern_fill_flat_u16.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint16),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
        lib.intern_fill_flat_i32.restype = ctypes.c_int64
        lib.intern_fill_flat_i32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
        lib.intern_count.restype = ctypes.c_int64
        lib.intern_count.argtypes = [ctypes.c_void_p]
        lib.intern_overflow.restype = ctypes.c_int
        lib.intern_overflow.argtypes = [ctypes.c_void_p]
        lib.intern_blob_bytes.restype = ctypes.c_int64
        lib.intern_blob_bytes.argtypes = [ctypes.c_void_p]
        lib.intern_dump.restype = None
        lib.intern_dump.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p]
        lib.intern_close.restype = None
        lib.intern_close.argtypes = [ctypes.c_void_p]
        lib.exact_emit_run.restype = ctypes.c_void_p
        lib.exact_emit_run.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64)]
        lib.exact_emit_total.restype = ctypes.c_int64
        lib.exact_emit_total.argtypes = [ctypes.c_void_p]
        lib.exact_emit_word_bytes.restype = ctypes.c_int64
        lib.exact_emit_word_bytes.argtypes = [ctypes.c_void_p]
        lib.exact_emit_line_bytes.restype = ctypes.c_int64
        lib.exact_emit_line_bytes.argtypes = [ctypes.c_void_p]
        lib.exact_emit_fill.restype = None
        lib.exact_emit_fill.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double), ctypes.c_char_p,
            ctypes.c_char_p]
        lib.exact_emit_free.restype = None
        lib.exact_emit_free.argtypes = [ctypes.c_void_p]
        _has_intern = True
    except AttributeError:  # stale .so predating the intern table
        pass
    try:
        lib.rerank_run.restype = ctypes.c_void_p
        lib.rerank_run.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int]
        lib.rerank_total.restype = ctypes.c_int64
        lib.rerank_total.argtypes = [ctypes.c_void_p]
        lib.rerank_blob_bytes.restype = ctypes.c_int64
        lib.rerank_blob_bytes.argtypes = [ctypes.c_void_p]
        lib.rerank_fill.restype = None
        lib.rerank_fill.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double), ctypes.c_char_p]
        lib.rerank_free.restype = None
        lib.rerank_free.argtypes = [ctypes.c_void_p]
        _has_rerank = True
    except AttributeError:  # stale .so predating rerank
        pass
    _lib = lib
    return _lib


def available() -> bool:
    """True when the native library is loadable."""
    return _load() is not None


def tokenize_hash_ids(data: bytes, vocab_size: int, seed: int = 0,
                      truncate_at: Optional[int] = None) -> Optional[np.ndarray]:
    """Native tokenize+hash: doc bytes -> int32 vocab ids.

    Returns None when the native library is unavailable (caller falls
    back to the Python path).
    """
    lib = _load()
    if lib is None:
        return None
    n = lib.tok_count(data, len(data))
    out = np.empty(n, dtype=np.int32)
    wrote = lib.tok_hash_ids(
        data, len(data), ctypes.c_uint64(seed), vocab_size,
        truncate_at or 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    assert wrote == n, f"tokenizer wrote {wrote} of {n} tokens"
    return out


def loader_available() -> bool:
    """True when the native parallel loader symbols are present."""
    return _load() is not None and _has_loader


def load_pack_paths(paths: List[str], vocab_size: int, seed: int = 0,
                    truncate_at: Optional[int] = None,
                    min_len: int = 1, chunk: int = 1,
                    pad_docs_to: Optional[int] = None,
                    n_threads: Optional[int] = None,
                    fixed_len: Optional[int] = None):
    """Native parallel read+tokenize+hash+pack (``native/loader.cc``).

    Reads every file with a work-stealing thread pool, then fills a
    padded ``[D, L]`` int32 id batch and a lengths vector with zero
    Python in the per-token loop. ``L`` = max(min_len, longest doc)
    rounded up to a ``chunk`` multiple — same shape rule as
    :func:`tfidf_tpu.io.corpus.pack_corpus`.

    ``fixed_len`` pins ``L`` exactly (documents beyond it are truncated
    to ``fixed_len`` tokens) — the static-shape mode for chunked ingest,
    where every chunk must share one compiled program.

    Returns ``(token_ids, lengths)`` or ``None`` when the native loader
    is unavailable. Raises FileNotFoundError on an unreadable file (the
    reference's hard exit, ``TFIDF.c:137``).
    """
    lib = _load()
    if lib is None or not _has_loader:
        return None
    n_threads = resolve_pack_threads(n_threads)
    blob = b"\0".join(p.encode() for p in paths) + b"\0"
    # fixed_len pins the batch shape, so the per-doc token counts are
    # never read — loader_open2(want_counts=0) skips that whole extra
    # scan of the corpus bytes (measured ~25% of pack on this host).
    if fixed_len is not None and _has_open2:
        handle = lib.loader_open2(blob, len(paths), n_threads, 0)
    else:
        handle = lib.loader_open(blob, len(paths), n_threads)
    try:
        err = lib.loader_error(handle)
        if err >= 0:
            raise FileNotFoundError(paths[err])
        if fixed_len is not None:
            padded_len = fixed_len  # loader_fill truncates rows at stride
        else:
            max_count = lib.loader_max_count(handle)
            padded_len = max(min_len, max_count, 1)
            padded_len = ((padded_len + chunk - 1) // chunk) * chunk
        d_padded = max(pad_docs_to or len(paths), len(paths))
        lengths = np.zeros((d_padded,), dtype=np.int32)
        lens_ptr = lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        # vocab <= 2^16: pack ids as uint16 — half the bytes on the
        # host->device wire; device kernels upcast to int32 for free.
        if vocab_size <= (1 << 16):
            ids = np.zeros((d_padded, padded_len), dtype=np.uint16)
            lib.loader_fill_u16(
                handle, ctypes.c_uint64(seed), vocab_size, truncate_at or 0,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                padded_len, lens_ptr, n_threads)
        else:
            ids = np.zeros((d_padded, padded_len), dtype=np.int32)
            lib.loader_fill(
                handle, ctypes.c_uint64(seed), vocab_size, truncate_at or 0,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                padded_len, lens_ptr, n_threads)
        return ids, lengths
    finally:
        lib.loader_close(handle)


def flat_available() -> bool:
    """True when the native ragged (flat) packer symbol is present."""
    return _load() is not None and _has_flat


def resolve_pack_threads(explicit: Optional[int] = None) -> int:
    """Host packer thread count: explicit arg > ``--pack-threads`` /
    ``TFIDF_TPU_PACK_THREADS`` env > every core (the paper's OpenMP
    default). Read at call time so tests can override after import;
    the bench artifact reports the resolved value."""
    if explicit is not None:
        n = int(explicit)
    else:
        raw = os.environ.get("TFIDF_TPU_PACK_THREADS")
        n = int(raw) if raw else (os.cpu_count() or 1)
    if n < 1:
        raise ValueError(
            f"TFIDF_TPU_PACK_THREADS must be >= 1, got {n}")
    return n


def _flat_pack_scaffold(lib, paths: List[str], max_per_doc: int,
                        pad_docs_to: Optional[int],
                        n_threads: Optional[int], fill,
                        dtype=np.uint16, align: int = 1,
                        cap_ids: Optional[int] = None):
    """Shared loader scaffolding of the flat packers (hashed and
    exact-id): path blob, parallel read (no count prepass), error
    mapping, buffer sizing, close. ``fill(handle, flat, lengths)``
    receives the numpy buffers, runs the per-token id pass, and
    returns total ids (or a negative sentinel the caller interprets).
    ``dtype`` is the wire id width (uint16, or int32 for wide caps);
    ``align`` is the granule-aligned wire layout (ingest._wire_align):
    each doc starts at a multiple of ``align`` ids. ``cap_ids``
    over-allocates the flat buffer to that many ids (callers pass the
    bucket-rounded chunk capacity so the downstream bucket pad never
    copies — the wire is emitted ragged AND ship-ready in one buffer)."""
    n_threads = n_threads or min(os.cpu_count() or 1, 16)
    blob = b"\0".join(p.encode() for p in paths) + b"\0"
    handle = lib.loader_open2(blob, len(paths), n_threads, 0)
    try:
        err = lib.loader_error(handle)
        if err >= 0:
            raise FileNotFoundError(paths[err])
        d_padded = max(pad_docs_to or len(paths), len(paths))
        per_doc_cap = max_per_doc if align <= 1 \
            else -(-max_per_doc // align) * align
        n_ids = max(len(paths) * per_doc_cap, cap_ids or 0)
        flat = np.empty((n_ids,), dtype=dtype)
        lengths = np.zeros((d_padded,), dtype=np.int32)
        total = fill(handle, flat, lengths)
        return flat, lengths, int(total)
    finally:
        lib.loader_close(handle)


def load_pack_flat(paths: List[str], vocab_size: int, seed: int = 0,
                   truncate_at: Optional[int] = None,
                   max_per_doc: int = 256,
                   pad_docs_to: Optional[int] = None,
                   n_threads: Optional[int] = None, align: int = 1,
                   cap_ids: Optional[int] = None):
    """Native ragged pack: read + tokenize + hash into a FLAT uint16
    stream (every doc back to back, no padding) plus per-doc lengths.

    The resident ingest path's wire format: the measured corpus wastes
    ~25% of a padded [D, L] batch on zero fill, and the tunneled link
    is the pipeline's floor, so the flat stream is what goes on the
    wire; the device rebuilds the padded batch with one gather
    (``ingest._chunk_ragged``). Requires vocab_size <= 2^16. Returns
    ``(flat_ids, lengths, total)`` with ``lengths`` padded to
    ``pad_docs_to`` rows, or None when the native packer is missing.

    ``cap_ids`` sizes the flat buffer to the bucket-rounded chunk
    capacity; with the v2 native fill the tail ``[total, cap_ids)`` is
    zero-filled in C++ too, so the buffer leaves native ragged AND
    ship-ready — no host-side re-pad pass at all.
    """
    lib = _load()
    if lib is None or not _has_flat or not _has_open2 \
            or vocab_size > (1 << 16):
        return None
    threads = resolve_pack_threads(n_threads)

    def fill(handle, flat, lens):
        # Threaded fill (round 14): the per-doc tokenize+hash loop —
        # the reference's OpenMP target — runs work-stolen across the
        # loader's ParallelFor pool. With one thread the serial v2/v1
        # fills keep their single-pass edge (v3 pays a count prepass).
        if _has_flat_v3 and threads > 1:
            return lib.loader_fill_flat_u16_v3(
                handle, ctypes.c_uint64(seed), vocab_size,
                truncate_at or 0, max_per_doc,
                flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                ctypes.c_int64(flat.size),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ctypes.c_int64(align), threads)
        if _has_flat_v2 and cap_ids:
            return lib.loader_fill_flat_u16_v2(
                handle, ctypes.c_uint64(seed), vocab_size,
                truncate_at or 0, max_per_doc,
                flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                ctypes.c_int64(flat.size),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ctypes.c_int64(align))
        return lib.loader_fill_flat_u16(
            handle, ctypes.c_uint64(seed), vocab_size, truncate_at or 0,
            max_per_doc,
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(align))

    return _flat_pack_scaffold(lib, paths, max_per_doc, pad_docs_to,
                               threads, fill, align=align,
                               cap_ids=cap_ids)


def slab_available() -> bool:
    """True when the native byte-slab loader symbols are present."""
    return _load() is not None and _has_slab and _has_open2


def load_slab_paths(paths: List[str], pad_docs_to: Optional[int] = None,
                    n_threads: Optional[int] = None, align: int = 16,
                    cap_round: int = 1):
    """Native bytes-wire pack: parallel file read + byte-slab fill —
    NO tokenize, NO hash, no id store on the host at all (the bytes
    wire's whole point; ``ops/device_tokenize.py`` has the layout
    contract). Returns ``(slab uint8 [cap], blens int32 [D_padded],
    total)`` where ``cap`` is the aligned total rounded up to a
    ``cap_round`` multiple and every non-document byte is ``0x20``, or
    None when the native slab loader is unavailable (the caller's
    Python fallback is contract-identical)."""
    lib = _load()
    if lib is None or not _has_slab or not _has_open2:
        return None
    threads = resolve_pack_threads(n_threads)
    blob = b"\0".join(p.encode() for p in paths) + b"\0"
    handle = lib.loader_open2(blob, len(paths), threads, 0)
    try:
        err = lib.loader_error(handle)
        if err >= 0:
            raise FileNotFoundError(paths[err])
        total = int(lib.loader_slab_bytes(handle, align))
        cap = max(total + (-total % cap_round), cap_round)
        d_padded = max(pad_docs_to or len(paths), len(paths))
        slab = np.empty((cap,), dtype=np.uint8)
        blens = np.zeros((d_padded,), dtype=np.int32)
        wrote = lib.loader_fill_slab(
            handle, slab.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            cap, blens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            align, threads)
        assert wrote == total, (wrote, total)
        return slab, blens, total
    finally:
        lib.loader_close(handle)


def rerank_available() -> bool:
    """True when the native exact-rerank symbols are present."""
    return _load() is not None and _has_rerank


def exact_rerank_paths(paths: List[str], topk_ids: np.ndarray,
                       num_docs_idf: int, vocab_size: int, seed: int = 0,
                       truncate_at: Optional[int] = None,
                       max_tokens: Optional[int] = None, k: int = 16,
                       n_threads: Optional[int] = None):
    """Native exact-string re-rank (``native/rerank.cc``).

    ``paths[i]`` is the document whose device top-k margin selection is
    ``topk_ids[i]`` (bucket ids, -1 padding). Returns a list (doc order)
    of ``[(word, score), ...]`` — exact float64 TF-IDF over exact DF of
    the candidate set, score-desc then word-asc, at most ``k`` entries,
    positive scores only. Returns None when the native engine is
    unavailable (caller falls back to the Python implementation, which
    is the semantics oracle — parity pinned by tests/test_rerank.py).

    Memory: the whole corpus is resident in the native arena for the
    two passes, like the loader path (≈ corpus bytes of host RAM).
    """
    lib = _load()
    if lib is None or not _has_rerank:
        return None
    n_docs = len(paths)
    topk_ids = np.ascontiguousarray(topk_ids, dtype=np.int32)
    # A malformed selection must fail loudly, not return empty top-k
    # lists (advisor r3: ndim != 2 silently produced kprime=0).
    assert topk_ids.ndim == 2 and topk_ids.shape[0] == n_docs, \
        (topk_ids.shape, n_docs)
    kprime = topk_ids.shape[1]
    n_threads = n_threads or min(os.cpu_count() or 1, 16)
    blob = b"\0".join(p.encode() for p in paths) + b"\0"
    handle = lib.loader_open2(blob, n_docs, n_threads, 0) \
        if _has_open2 else lib.loader_open(blob, n_docs, n_threads)
    res = None
    try:
        err = lib.loader_error(handle)
        if err >= 0:
            raise FileNotFoundError(paths[err])
        res = lib.rerank_run(
            handle, topk_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            kprime, num_docs_idf, ctypes.c_uint64(seed), vocab_size,
            truncate_at or 0, max_tokens or 0, k, n_threads)
        total = lib.rerank_total(res)
        counts = np.zeros((n_docs,), dtype=np.int32)
        offs = np.zeros((total,), dtype=np.int64)
        lens = np.zeros((total,), dtype=np.int64)
        scores = np.zeros((total,), dtype=np.float64)
        blob_out = ctypes.create_string_buffer(
            max(int(lib.rerank_blob_bytes(res)), 1))
        lib.rerank_fill(
            res, counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            scores.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            blob_out)
        words_blob = blob_out.raw
        out = []
        pos = 0
        off_l = offs.tolist()
        len_l = lens.tolist()
        sc_l = scores.tolist()
        for d in range(n_docs):
            c = int(counts[d])
            out.append([(words_blob[off_l[j]:off_l[j] + len_l[j]], sc_l[j])
                        for j in range(pos, pos + c)])
            pos += c
        return out
    finally:
        if res is not None:
            lib.rerank_free(res)
        lib.loader_close(handle)


def intern_available() -> bool:
    """True when the native exact-id intern symbols are present."""
    return _load() is not None and _has_intern


class ExactVocabOverflow(Exception):
    """More distinct words than the configured vocab — the exact-id
    fast path cannot serve this corpus; fall back to hashed+rerank."""


class InternSession:
    """A run-scoped exact word-id table (``native/intern.cc``).

    Shared across every chunk of an overlapped ingest so ids are
    corpus-global; ``words()`` dumps the id -> bytes dictionary at the
    end. Use as a context manager (the table is native memory).
    """

    def __init__(self, cap: int):
        lib = _load()
        if lib is None or not _has_intern:
            raise RuntimeError("native intern table unavailable")
        self._lib = lib
        self._cap = cap
        self._h = lib.intern_open(cap)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        if self._h is not None:
            self._lib.intern_close(self._h)
            self._h = None

    @property
    def count(self) -> int:
        return int(self._lib.intern_count(self._h))

    def pack_flat(self, paths: List[str], truncate_at: Optional[int],
                  max_per_doc: int, pad_docs_to: Optional[int] = None,
                  seed: int = 0, n_threads: Optional[int] = None,
                  align: int = 1, cap_ids: Optional[int] = None):
        """Exact-id twin of :func:`load_pack_flat` (same return
        contract, shared loader scaffold, same ``cap_ids`` bucket-
        capacity staging). The wire is uint16 up to a 2^16 cap and
        int32 beyond (wide-vocab exact mode). Raises
        :class:`ExactVocabOverflow` when the corpus holds more distinct
        words than the table's cap."""
        lib = self._lib
        wide = self._cap > (1 << 16)
        fill_fn = lib.intern_fill_flat_i32 if wide \
            else lib.intern_fill_flat_u16
        id_ct = ctypes.c_int32 if wide else ctypes.c_uint16

        def fill(handle, flat, lens):
            return fill_fn(
                handle, self._h, ctypes.c_uint64(seed), truncate_at or 0,
                max_per_doc,
                flat.ctypes.data_as(ctypes.POINTER(id_ct)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ctypes.c_int64(align))

        flat, lengths, total = _flat_pack_scaffold(
            lib, paths, max_per_doc, pad_docs_to, n_threads, fill,
            dtype=np.int32 if wide else np.uint16, align=align,
            cap_ids=cap_ids)
        if total < 0:
            raise ExactVocabOverflow(
                f"corpus exceeds {self.count} distinct words")
        return flat, lengths, total

    def emit(self, input_dir: str, names: List[str],
             topk_ids: np.ndarray, topk_counts: np.ndarray,
             df: np.ndarray, lengths: np.ndarray, num_docs: int, k: int,
             truncate_at: Optional[int], max_tokens: Optional[int],
             seed: int = 0, n_threads: Optional[int] = None):
        """Native exact-terms finish (``intern.cc exact_emit``): float64
        rescore, per-doc (-score, word) sort, reference-format lines,
        global byte-lex sort — plus doc-major (word, score) arrays for
        recall consumers. Returns ``(lines, per_doc_counts, offs, lens,
        scores, word_blob)`` where ``lines`` is the final sorted output
        bytes."""
        lib = self._lib
        n_docs = len(names)
        kprime = topk_ids.shape[1] if topk_ids.ndim == 2 else 0
        assert topk_ids.ndim == 2 and topk_ids.shape[0] == n_docs
        ids = np.ascontiguousarray(topk_ids, dtype=np.int32)
        cnt = np.ascontiguousarray(topk_counts, dtype=np.int32)
        dfv = np.ascontiguousarray(df, dtype=np.int32)
        lens_arr = np.ascontiguousarray(lengths[:n_docs], dtype=np.int32)
        blob = b"\0".join(n.encode() for n in names) + b"\0"
        i32p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        failed = np.full((1,), -1, dtype=np.int64)
        res = lib.exact_emit_run(
            self._h, input_dir.encode(), blob, i32p(ids), i32p(cnt),
            n_docs, kprime, i32p(dfv), dfv.size, i32p(lens_arr),
            num_docs, k, truncate_at or 0, max_tokens or 0,
            ctypes.c_uint64(seed),
            n_threads or min(os.cpu_count() or 1, 16),
            failed.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if not res:
            # A boundary-tie document vanished between pack and emit —
            # fail loudly like the Python twin (_doc_words).
            raise FileNotFoundError(
                os.path.join(input_dir, names[int(failed[0])])
                if failed[0] >= 0 else input_dir)
        try:
            total = int(lib.exact_emit_total(res))
            per_doc = np.zeros((n_docs,), dtype=np.int32)
            offs = np.zeros((max(total, 1),), dtype=np.int64)
            lens_out = np.zeros((max(total, 1),), dtype=np.int64)
            scores = np.zeros((max(total, 1),), dtype=np.float64)
            wblob = ctypes.create_string_buffer(
                max(int(lib.exact_emit_word_bytes(res)), 1))
            lblob = ctypes.create_string_buffer(
                max(int(lib.exact_emit_line_bytes(res)), 1))
            lib.exact_emit_fill(
                res, i32p(per_doc),
                offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                lens_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                scores.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                wblob, lblob)
            return (lblob.raw[:int(lib.exact_emit_line_bytes(res))],
                    per_doc, offs, lens_out, scores, wblob.raw)
        finally:
            lib.exact_emit_free(res)

    def words(self) -> List[bytes]:
        """The id -> word dictionary, index = exact id."""
        lib = self._lib
        n = self.count
        offs = np.zeros((max(n, 1),), dtype=np.int64)
        lens = np.zeros((max(n, 1),), dtype=np.int64)
        blob = ctypes.create_string_buffer(
            max(int(lib.intern_blob_bytes(self._h)), 1))
        lib.intern_dump(
            self._h, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), blob)
        raw = blob.raw
        return [raw[offs[i]:offs[i] + lens[i]] for i in range(n)]


def tokenize_spans(data: bytes) -> Optional[List[bytes]]:
    """Native tokenization returning token byte-strings (EXACT mode)."""
    lib = _load()
    if lib is None:
        return None
    n = lib.tok_count(data, len(data))
    offs = np.empty(n, dtype=np.int64)
    lens = np.empty(n, dtype=np.int64)
    wrote = lib.tok_spans(
        data, len(data),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n)
    assert wrote == n
    return [data[o:o + l] for o, l in zip(offs.tolist(), lens.tolist())]
