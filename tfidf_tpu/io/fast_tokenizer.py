"""ctypes bindings for the native tokenize+hash kernel.

``native/fast_tokenizer.cc`` implements the loader's host hot loop
(tokenize -> FNV-1a -> fold) in one C++ pass; this module exposes it to
Python and transparently falls back to the pure-Python implementation
when the shared library has not been built (``make -C native``).

The native and Python paths are contract-identical (pinned by
tests/test_native.py), so callers never need to know which ran.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "fast_tokenizer.so")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False
_has_loader = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed, _has_loader
    # The kill-switch wins even over an already-loaded library, and a
    # missing .so is not sticky (tests build it on demand mid-process).
    if os.environ.get("TFIDF_TPU_NO_NATIVE"):
        return None
    if _lib is not None or _load_failed:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        _load_failed = True
        return None
    lib.tok_count.restype = ctypes.c_int64
    lib.tok_count.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.tok_hash_ids.restype = ctypes.c_int64
    lib.tok_hash_ids.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.tok_spans.restype = ctypes.c_int64
    lib.tok_spans.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    try:
        lib.loader_open.restype = ctypes.c_void_p
        lib.loader_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
        lib.loader_error.restype = ctypes.c_int64
        lib.loader_error.argtypes = [ctypes.c_void_p]
        lib.loader_token_count.restype = ctypes.c_int64
        lib.loader_token_count.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.loader_max_count.restype = ctypes.c_int64
        lib.loader_max_count.argtypes = [ctypes.c_void_p]
        lib.loader_fill.restype = None
        lib.loader_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        lib.loader_fill_u16.restype = None
        lib.loader_fill_u16.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint16), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        lib.loader_close.restype = None
        lib.loader_close.argtypes = [ctypes.c_void_p]
        _has_loader = True
    except AttributeError:  # stale .so predating the loader
        pass
    _lib = lib
    return _lib


def available() -> bool:
    """True when the native library is loadable."""
    return _load() is not None


def tokenize_hash_ids(data: bytes, vocab_size: int, seed: int = 0,
                      truncate_at: Optional[int] = None) -> Optional[np.ndarray]:
    """Native tokenize+hash: doc bytes -> int32 vocab ids.

    Returns None when the native library is unavailable (caller falls
    back to the Python path).
    """
    lib = _load()
    if lib is None:
        return None
    n = lib.tok_count(data, len(data))
    out = np.empty(n, dtype=np.int32)
    wrote = lib.tok_hash_ids(
        data, len(data), ctypes.c_uint64(seed), vocab_size,
        truncate_at or 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    assert wrote == n, f"tokenizer wrote {wrote} of {n} tokens"
    return out


def loader_available() -> bool:
    """True when the native parallel loader symbols are present."""
    return _load() is not None and _has_loader


def load_pack_paths(paths: List[str], vocab_size: int, seed: int = 0,
                    truncate_at: Optional[int] = None,
                    min_len: int = 1, chunk: int = 1,
                    pad_docs_to: Optional[int] = None,
                    n_threads: Optional[int] = None,
                    fixed_len: Optional[int] = None):
    """Native parallel read+tokenize+hash+pack (``native/loader.cc``).

    Reads every file with a work-stealing thread pool, then fills a
    padded ``[D, L]`` int32 id batch and a lengths vector with zero
    Python in the per-token loop. ``L`` = max(min_len, longest doc)
    rounded up to a ``chunk`` multiple — same shape rule as
    :func:`tfidf_tpu.io.corpus.pack_corpus`.

    ``fixed_len`` pins ``L`` exactly (documents beyond it are truncated
    to ``fixed_len`` tokens) — the static-shape mode for chunked ingest,
    where every chunk must share one compiled program.

    Returns ``(token_ids, lengths)`` or ``None`` when the native loader
    is unavailable. Raises FileNotFoundError on an unreadable file (the
    reference's hard exit, ``TFIDF.c:137``).
    """
    lib = _load()
    if lib is None or not _has_loader:
        return None
    n_threads = n_threads or min(os.cpu_count() or 1, 16)
    blob = b"\0".join(p.encode() for p in paths) + b"\0"
    handle = lib.loader_open(blob, len(paths), n_threads)
    try:
        err = lib.loader_error(handle)
        if err >= 0:
            raise FileNotFoundError(paths[err])
        if fixed_len is not None:
            padded_len = fixed_len  # loader_fill truncates rows at stride
        else:
            max_count = lib.loader_max_count(handle)
            padded_len = max(min_len, max_count, 1)
            padded_len = ((padded_len + chunk - 1) // chunk) * chunk
        d_padded = max(pad_docs_to or len(paths), len(paths))
        lengths = np.zeros((d_padded,), dtype=np.int32)
        lens_ptr = lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        # vocab <= 2^16: pack ids as uint16 — half the bytes on the
        # host->device wire; device kernels upcast to int32 for free.
        if vocab_size <= (1 << 16):
            ids = np.zeros((d_padded, padded_len), dtype=np.uint16)
            lib.loader_fill_u16(
                handle, ctypes.c_uint64(seed), vocab_size, truncate_at or 0,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                padded_len, lens_ptr, n_threads)
        else:
            ids = np.zeros((d_padded, padded_len), dtype=np.int32)
            lib.loader_fill(
                handle, ctypes.c_uint64(seed), vocab_size, truncate_at or 0,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                padded_len, lens_ptr, n_threads)
        return ids, lengths
    finally:
        lib.loader_close(handle)


def tokenize_spans(data: bytes) -> Optional[List[bytes]]:
    """Native tokenization returning token byte-strings (EXACT mode)."""
    lib = _load()
    if lib is None:
        return None
    n = lib.tok_count(data, len(data))
    offs = np.empty(n, dtype=np.int64)
    lens = np.empty(n, dtype=np.int64)
    wrote = lib.tok_spans(
        data, len(data),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n)
    assert wrote == n
    return [data[o:o + l] for o, l in zip(offs.tolist(), lens.tolist())]
