"""Corpus IO: discovery, loading, and static-shape packing."""

from tfidf_tpu.io.corpus import Corpus, PackedBatch, discover_corpus, pack_corpus

__all__ = ["Corpus", "PackedBatch", "discover_corpus", "pack_corpus"]
