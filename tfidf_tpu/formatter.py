"""Host-side result formatting with reference byte-parity.

The device computes *exact integers* (TF counts, doc lengths, DF); this
module performs the final double math on host in the same operation order
as the C reference (``TFIDF.c:202,243-245``) and emits the same
``document@word\\t%.16f`` lines in the same ``strcmp`` order
(``TFIDF.c:273``). Splitting the pipeline there is what lets the TPU side
run in float32/bfloat16 while the emitted file is still byte-identical to
the reference (SURVEY §7 "hard parts": bit-identical output).
"""

from __future__ import annotations

import math
from typing import Dict, List, Sequence

import numpy as np


def _record_line(name: str, word: bytes, count: int, doc_size: int,
                 df_v: int, num_docs: int) -> bytes:
    """ONE (document, word) output line — the byte-parity-critical math.

    Shared by the dense and sparse formatters so the reference semantics
    (op order and %.16f formatting) live in exactly one place:
    TF = 1.0*count/docSize (``TFIDF.c:202``), IDF = log(1.0*N/DF)
    (``TFIDF.c:243``), line = document@word\\t%.16f (``TFIDF.c:245``).
    """
    tf = 1.0 * count / doc_size
    idf = math.log(1.0 * num_docs / df_v)
    score = tf * idf
    return b"%s@%s\t%s" % (name.encode(), word, b"%.16f" % score)


def format_records(counts: np.ndarray, lengths: np.ndarray, df: np.ndarray,
                   num_docs: int, names: Sequence[str],
                   id_to_word: Dict[int, bytes]) -> List[bytes]:
    """Golden-format lines from integer pipeline outputs.

    Args:
      counts: int [D, V] per-doc term counts (padding docs all-zero).
      lengths: int [D] docSize per doc.
      df: int [V] global document frequencies.
      num_docs: real (unpadded) document count N.
      names: D document names; '' entries (mesh padding) are skipped.
      id_to_word: id -> token bytes for every id with nonzero counts.
    """
    counts = np.asarray(counts)
    lengths = np.asarray(lengths)
    df = np.asarray(df)
    lines: List[bytes] = []
    docs_idx, vocab_idx = np.nonzero(counts)
    for d, v in zip(docs_idx.tolist(), vocab_idx.tolist()):
        name = names[d]
        if not name:
            continue
        lines.append(_record_line(name, id_to_word[v], int(counts[d, v]),
                                  int(lengths[d]), int(df[v]), num_docs))
    lines.sort()
    return lines


def format_sparse_records(ids: np.ndarray, counts: np.ndarray,
                          head: np.ndarray, lengths: np.ndarray,
                          df: np.ndarray, num_docs: int,
                          names: Sequence[str],
                          id_to_word: Dict[int, bytes]) -> List[bytes]:
    """Golden-format lines from the row-sparse engine's outputs.

    Same math and ordering as :func:`format_records`, sourced from
    (ids, counts, head) [D, L] triples instead of a dense [D, V] matrix.
    """
    ids, counts = np.asarray(ids), np.asarray(counts)
    head, lengths, df = np.asarray(head), np.asarray(lengths), np.asarray(df)
    lines: List[bytes] = []
    docs_idx, slot_idx = np.nonzero(head)
    for d, i in zip(docs_idx.tolist(), slot_idx.tolist()):
        name = names[d]
        if not name:
            continue
        v = int(ids[d, i])
        lines.append(_record_line(name, id_to_word[v], int(counts[d, i]),
                                  int(lengths[d]), int(df[v]), num_docs))
    lines.sort()
    return lines


def to_output_bytes(lines: Sequence[bytes]) -> bytes:
    """Join lines into the ``output.txt`` byte stream (``TFIDF.c:278-281``)."""
    return b"".join(line + b"\n" for line in lines)


def write_output(path: str, lines: Sequence[bytes]) -> None:
    with open(path, "wb") as f:
        f.write(to_output_bytes(lines))
