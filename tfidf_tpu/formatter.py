"""Host-side result formatting with reference byte-parity.

The device computes *exact integers* (TF counts, doc lengths, DF); this
module performs the final double math on host in the same operation order
as the C reference (``TFIDF.c:202,243-245``) and emits the same
``document@word\\t%.16f`` lines in the same ``strcmp`` order
(``TFIDF.c:273``). Splitting the pipeline there is what lets the TPU side
run in float32/bfloat16 while the emitted file is still byte-identical to
the reference (SURVEY §7 "hard parts": bit-identical output).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np


def format_records(counts: np.ndarray, lengths: np.ndarray, df: np.ndarray,
                   num_docs: int, names: Sequence[str],
                   id_to_word: Dict[int, bytes]) -> List[bytes]:
    """Golden-format lines from integer pipeline outputs.

    Args:
      counts: int [D, V] per-doc term counts (padding docs all-zero).
      lengths: int [D] docSize per doc.
      df: int [V] global document frequencies.
      num_docs: real (unpadded) document count N.
      names: D document names; '' entries (mesh padding) are skipped.
      id_to_word: id -> token bytes for every id with nonzero counts.
    """
    counts = np.asarray(counts)
    lengths = np.asarray(lengths)
    df = np.asarray(df)
    lines: List[bytes] = []
    docs_idx, vocab_idx = np.nonzero(counts)
    for d, v in zip(docs_idx.tolist(), vocab_idx.tolist()):
        name = names[d]
        if not name:
            continue
        c = int(counts[d, v])
        tf = 1.0 * c / int(lengths[d])            # TFIDF.c:202
        idf = math.log(1.0 * num_docs / int(df[v]))  # TFIDF.c:243
        score = tf * idf                           # TFIDF.c:244
        lines.append(b"%s@%s\t%s" % (
            name.encode(), id_to_word[v], b"%.16f" % score))
    lines.sort()
    return lines


def to_output_bytes(lines: Sequence[bytes]) -> bytes:
    """Join lines into the ``output.txt`` byte stream (``TFIDF.c:278-281``)."""
    return b"".join(line + b"\n" for line in lines)


def write_output(path: str, lines: Sequence[bytes]) -> None:
    with open(path, "wb") as f:
        f.write(to_output_bytes(lines))
