"""Checkpoint/resume for streaming TF-IDF state.

The reference is a single-shot batch job: its only durable artifact is
the final ``output.txt`` (``TFIDF.c:274-282``), and a crash means
rerunning the whole corpus (SURVEY §5, checkpoint row: ABSENT). Here the
streaming engine's state — the incremental DF vector plus the documents
-seen counter (``streaming.StreamingTfidf``) — can be persisted between
minibatches and restored in a fresh process, so a long corpus stream
survives preemption (the BASELINE config-5 capability).

Crash-safety protocol (both backends): each save writes a fresh payload
directory ``ckpt-<seq>/`` under the checkpoint root, then atomically
repoints the ``LATEST`` file at it (``os.replace`` of a one-line file),
then deletes superseded payloads. A crash at *any* instant leaves either
the old committed checkpoint or the new one — never neither. (Orbax's
own ``Checkpointer.save(force=True)`` deletes the previous checkpoint
before writing the replacement, so pointing it at a fixed directory has
a lose-everything window; the seq+pointer layer closes it.)

Payload backend: Orbax's PyTreeCheckpointer (handles sharded arrays)
when importable; otherwise a plain ``.npz``. Both produce/consume the
same logical state dict.

Since round 13 the SAME seq+LATEST protocol also persists the built
retriever index (:func:`save_index` / :func:`restore_index`): CSR
arrays + IDF + doc names + caller metadata (epoch, config
fingerprint), each array sha256-checksummed so silent disk corruption
raises the typed :class:`SnapshotMismatch` instead of serving wrong
bytes. This is what lets a SIGKILLed ``tfidf serve --snapshot-dir``
process resume serving in seconds instead of re-ingesting the corpus
(tests/test_snapshot.py pins the crash windows).
"""

from __future__ import annotations

import contextlib
import fcntl
import hashlib
import json
import os
import shutil
import tempfile
from typing import Callable, Dict, Iterator, Tuple

import numpy as np


class SnapshotMismatch(ValueError):
    """A committed snapshot cannot serve this process: a checksum
    failed (corruption) or the config fingerprint differs from the
    running config (restoring it would silently serve wrong results).
    Callers fall back to a rebuild."""

try:  # orbax is in the image; guard anyway so the npz path self-heals
    import orbax.checkpoint as _ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    _ocp = None
    _HAVE_ORBAX = False

_NPZ_NAME = "state.npz"
_LATEST = "LATEST"
_LOCK = "LOCK"


@contextlib.contextmanager
def _writer_lock(path: str) -> Iterator[None]:
    """Advisory single-writer lock on the checkpoint root.

    ``save_state`` assumes one writer per root: its debris sweep deletes
    every uncommitted ``ckpt-*`` entry, so a second concurrent saver's
    in-flight payload would be destroyed mid-write. The flock makes that
    contract enforced — a concurrent save raises instead of corrupting —
    and cannot go stale (the kernel drops flocks when the holder dies).
    """
    fd = os.open(os.path.join(path, _LOCK), os.O_CREAT | os.O_RDWR, 0o644)
    try:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except BlockingIOError:
            raise RuntimeError(
                f"another process is saving a checkpoint under {path}; "
                "save_state is single-writer per checkpoint root")
        yield
    finally:
        os.close(fd)  # releases the flock


def _fsync_dir(path: str) -> None:
    """Make directory-entry changes (create/rename/unlink) durable."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover — e.g. platforms without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _reclaim_debris(path: str, keep: str | None) -> None:
    """Remove every payload/tmp entry except ``keep`` (the committed one).

    Covers uncommitted ``ckpt-<n>`` dirs from a save that crashed before
    the LATEST repoint, orphaned superseded payloads from a crash
    *after* the repoint but before their rmtree, orbax's
    ``*.orbax-checkpoint-tmp-*`` staging dirs, and stale
    ``*.latest.tmp`` pointer files.
    """
    for entry in os.listdir(path):
        if entry == _LATEST or entry == keep:
            continue
        if entry.startswith("ckpt-") or entry.endswith(".latest.tmp"):
            full = os.path.join(path, entry)
            if os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
            else:
                try:
                    os.unlink(full)
                except OSError:  # pragma: no cover
                    pass


def _committed_payload(path: str):
    """(payload_dir, seq) of the committed checkpoint, or (None, -1)."""
    latest = os.path.join(path, _LATEST)
    try:
        with open(latest, "r") as f:
            name = f.read().strip()
    except OSError:
        return None, -1
    payload = os.path.join(path, name)
    if not os.path.isdir(payload):
        return None, -1  # pointer ahead of a crashed/garbage-collected dir
    try:
        seq = int(name.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        seq = 0
    return payload, seq


def _commit_payload(path: str, write_payload: Callable[[str], None]
                    ) -> None:
    """The shared crash-safety protocol: write a fresh ``ckpt-<seq>``
    payload via ``write_payload(payload_dir)``, then atomically
    repoint ``LATEST``, then drop the superseded payload. A crash at
    any instant leaves the old committed checkpoint or the new one —
    never neither. Single-writer per root (flock-enforced)."""
    os.makedirs(path, exist_ok=True)
    with _writer_lock(path):
        old_payload, seq = _committed_payload(path)
        _reclaim_debris(path,
                        os.path.basename(old_payload) if old_payload else None)
        name = f"ckpt-{seq + 1}"
        payload = os.path.join(path, name)
        write_payload(payload)
        _fsync_dir(path)  # make the new payload's dirent durable pre-commit

        # Commit: atomically repoint LATEST, then drop superseded payload.
        fd, tmp = tempfile.mkstemp(dir=path, suffix=".latest.tmp")
        with os.fdopen(fd, "w") as f:
            f.write(name)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(path, _LATEST))
        _fsync_dir(path)  # rename must hit disk before old payload goes
        if old_payload and os.path.isdir(old_payload):
            shutil.rmtree(old_payload, ignore_errors=True)


def save_state(path: str, state: Dict[str, np.ndarray],
               force_npz: bool = False) -> str:
    """Persist a streaming state dict under the checkpoint root ``path``.

    Returns the payload backend used ("orbax" or "npz"). The previous
    checkpoint stays restorable until the new one is committed.

    Single-writer per checkpoint root (enforced): a concurrent
    ``save_state`` on the same ``path`` raises ``RuntimeError`` rather
    than racing the debris sweep. Concurrent *readers* are always safe —
    ``restore_state`` only follows the committed ``LATEST`` pointer.
    """
    state = {k: np.asarray(v) for k, v in state.items()}
    backend = []

    def write_payload(payload: str) -> None:
        if _HAVE_ORBAX and not force_npz:
            _ocp.PyTreeCheckpointer().save(os.path.abspath(payload), state)
            backend.append("orbax")
        else:
            os.makedirs(payload)
            with open(os.path.join(payload, _NPZ_NAME), "wb") as f:
                np.savez(f, **state)
                f.flush()
                os.fsync(f.fileno())
            backend.append("npz")

    _commit_payload(path, write_payload)
    return backend[0]


def restore_state(path: str) -> Dict[str, np.ndarray]:
    """Load the committed state dict written by :func:`save_state`."""
    payload, _ = _committed_payload(path)
    if payload is None:
        raise FileNotFoundError(f"no committed checkpoint at {path}")
    npz_path = os.path.join(payload, _NPZ_NAME)
    if os.path.exists(npz_path):
        with np.load(npz_path) as data:
            return {k: data[k] for k in data.files}
    if _HAVE_ORBAX:
        restored = _ocp.PyTreeCheckpointer().restore(os.path.abspath(payload))
        return {k: np.asarray(v) for k, v in restored.items()}
    raise FileNotFoundError(  # pragma: no cover — orbax payload, no orbax
        f"checkpoint at {path} needs orbax to restore")


def exists(path: str) -> bool:
    """True when ``path`` holds a committed, restorable checkpoint."""
    return _committed_payload(path)[0] is not None


# --- index snapshots (round 13) --------------------------------------

_INDEX_NPZ = "index.npz"
_INDEX_META = "meta.json"
INDEX_SCHEMA = "tfidf-index/1"


def _array_sha(arr: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def save_index(path: str, arrays: Dict[str, np.ndarray],
               meta: Dict) -> str:
    """Persist a built retriever index under the checkpoint root
    ``path`` with the same seq+LATEST protocol as :func:`save_state`.

    The payload is one plain ``index.npz`` (portable — restoring
    needs numpy, not orbax) plus ``meta.json`` carrying the caller's
    metadata (epoch, config fingerprint, doc count) and a sha256
    checksum per array; :func:`restore_index` re-verifies them, so a
    torn or bit-rotted snapshot raises :class:`SnapshotMismatch`
    instead of silently serving wrong results. Returns ``path``."""
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    doc = {
        "schema": INDEX_SCHEMA,
        "meta": dict(meta),
        "checksums": {k: _array_sha(v) for k, v in arrays.items()},
        "arrays": {k: {"dtype": str(v.dtype), "shape": list(v.shape)}
                   for k, v in arrays.items()},
    }

    def write_payload(payload: str) -> None:
        os.makedirs(payload)
        with open(os.path.join(payload, _INDEX_NPZ), "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(payload, _INDEX_META), "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())

    _commit_payload(path, write_payload)
    return path


def restore_index(path: str) -> Tuple[Dict[str, np.ndarray], Dict]:
    """Load the committed index snapshot: ``(arrays, meta)``.

    Raises ``FileNotFoundError`` when no committed snapshot exists and
    :class:`SnapshotMismatch` when the payload fails its schema or
    checksum validation (the caller falls back to a rebuild)."""
    payload, _ = _committed_payload(path)
    if payload is None:
        raise FileNotFoundError(f"no committed index snapshot at {path}")
    meta_path = os.path.join(payload, _INDEX_META)
    npz_path = os.path.join(payload, _INDEX_NPZ)
    if not os.path.exists(meta_path) or not os.path.exists(npz_path):
        raise SnapshotMismatch(
            f"committed payload {payload} is not an index snapshot "
            f"(state checkpoint? missing meta/npz)")
    with open(meta_path) as f:
        doc = json.load(f)
    if doc.get("schema") != INDEX_SCHEMA:
        raise SnapshotMismatch(
            f"index snapshot schema {doc.get('schema')!r} != "
            f"{INDEX_SCHEMA!r}")
    with np.load(npz_path) as data:
        arrays = {k: data[k] for k in data.files}
    checksums = doc.get("checksums", {})
    if set(checksums) != set(arrays):
        raise SnapshotMismatch(
            f"index snapshot arrays {sorted(arrays)} != checksummed "
            f"set {sorted(checksums)}")
    for name, arr in arrays.items():
        got = _array_sha(arr)
        if got != checksums[name]:
            raise SnapshotMismatch(
                f"index snapshot array {name!r} fails its checksum "
                f"({got[:12]}... != {checksums[name][:12]}...) — "
                f"corrupt payload")
    return arrays, dict(doc.get("meta", {}))
