"""Supervised execution: retry, circuit breaking, poison isolation.

The serving layer before this module could *see* trouble (health,
canary, flight recorder) but not *survive* it: one transient device
error failed every query coalesced into the batch, and a query that
deterministically crashes the kernel would crash every batch it ever
rides in. This module is the recovery half, three pieces:

* :class:`RetryPolicy` + :meth:`SupervisedDispatch.run` — bounded
  retry with jittered exponential backoff for **transient** dispatch
  failures (typed :class:`~tfidf_tpu.faults.TransientFault`, plus
  anything the caller's classifier deems retryable). Each retry is a
  ``dispatch_retry`` span on the batcher lane (nested inside the
  batch's ``batched`` span — ``tools/trace_check.py`` pins the
  nesting), a flight event, and a ``serve_dispatch_retries_total``
  count.
* :class:`CircuitBreaker` — trips OPEN after N consecutive dispatch
  failures. An open breaker does NOT stop the batcher (queued batches
  are the recovery probes); it reports a degraded reason through
  :meth:`CircuitBreaker.health_signal`, which shrinks the admission
  bound exactly like queue saturation does — the "trips into degraded
  admission" feedback. After ``cooldown_s`` the breaker is HALF-OPEN;
  the next dispatch success closes it (flight events both ways).
* :meth:`SupervisedDispatch.run_batch` — when a batch fails past its
  retry budget, **bisect**: recursively dispatch halves until the
  failure is pinned to single queries. The isolated queries are
  poison (their requests fail with the typed :class:`PoisonQuery` and
  the server quarantines them — served 4xx thereafter); every
  innocent co-batched query still returns the bit-identical rows a
  clean dispatch would have produced (per-query results are
  independent — the same property that lets the batcher slice
  coalesced batches per request).

The :class:`QuarantineList` lives here too: a bounded set of
normalized poison-query keys the server consults at admission, with a
``serve_quarantine_size`` gauge and ``serve_quarantined_total``
counter.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from tfidf_tpu import faults, obs
from tfidf_tpu.obs import log as obs_log
from tfidf_tpu.serve.batcher import PoisonQuery  # noqa: F401 re-export

__all__ = ["PoisonQuery", "RetryPolicy", "CircuitBreaker",
           "QuarantineList", "SupervisedDispatch"]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with jittered exponential backoff.

    ``max_attempts`` counts dispatch attempts INCLUDING the first
    (1 = no retry). Backoff between attempts is
    ``base * mult^(n-1)`` capped at ``cap``, jittered +-``jitter``
    fraction from a ``random.Random(seed)`` — deterministic per
    policy instance, so chaos runs replay."""

    max_attempts: int = 3
    backoff_ms: float = 10.0
    backoff_mult: float = 2.0
    max_backoff_ms: float = 1000.0
    jitter: float = 0.5
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.backoff_ms < 0 or self.max_backoff_ms < 0:
            raise ValueError("backoff must be >= 0")


class CircuitBreaker:
    """Consecutive-failure breaker with a cooldown half-open state.

    ``closed`` (healthy) -> ``open`` after ``threshold`` consecutive
    failures -> ``half_open`` once ``cooldown_s`` elapses -> the next
    success closes it (a failure re-opens and restarts the cooldown).
    Thread-safe; publishes ``serve_breaker_open`` (0/1) and
    ``serve_breaker_trips_total`` when given a registry, and exposes
    the :meth:`health_signal` hook that turns an open breaker into a
    degraded admission bound."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0,
                 registry=None) -> None:
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        if cooldown_s <= 0:
            raise ValueError("cooldown_s must be positive")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._consecutive = 0
        self._open_since: Optional[float] = None
        self._g_open = self._c_trips = None
        if registry is not None:
            self._g_open = registry.gauge(
                "serve_breaker_open",
                "dispatch circuit breaker: 1 while open/half-open")
            self._c_trips = registry.counter(
                "serve_breaker_trips_total",
                "circuit breaker trips (N consecutive dispatch "
                "failures)")

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked(time.monotonic())

    def _state_locked(self, now: float) -> str:
        if self._open_since is None:
            return "closed"
        if now - self._open_since >= self.cooldown_s:
            return "half_open"
        return "open"

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive

    def record_failure(self) -> bool:
        """Count one dispatch failure; returns True when this one
        tripped the breaker open."""
        now = time.monotonic()
        with self._lock:
            self._consecutive += 1
            if self._open_since is not None:
                # A half-open trial failed: restart the cooldown.
                self._open_since = now
                return False
            if self._consecutive < self.threshold:
                return False
            self._open_since = now
        if self._c_trips is not None:
            self._c_trips.inc()
        if self._g_open is not None:
            self._g_open.set(1)
        obs_log.log_event(
            "error", "breaker_trip",
            msg=f"circuit breaker OPEN after {self._consecutive} "
                f"consecutive dispatch failures "
                f"(cooldown {self.cooldown_s}s)",
            consecutive=self._consecutive)
        return True

    def record_success(self) -> None:
        with self._lock:
            was_open = self._open_since is not None
            self._consecutive = 0
            self._open_since = None
        if was_open:
            if self._g_open is not None:
                self._g_open.set(0)
            obs_log.log_event("info", "breaker_close",
                              msg="circuit breaker closed "
                                  "(dispatch succeeded)")

    def cooldown_remaining(self) -> float:
        """Seconds until the open breaker goes half-open (0 when
        closed or already half-open)."""
        with self._lock:
            if self._open_since is None:
                return 0.0
            return max(0.0, self.cooldown_s
                       - (time.monotonic() - self._open_since))

    def health_signal(self) -> Tuple[object, Optional[str]]:
        """:meth:`HealthMonitor.add_signal` hook: (state, reason).
        Any non-closed state is a degraded reason — the admission
        bound shrinks while the breaker is open, which is how a
        failing device sheds load at the gate instead of queueing
        doomed work."""
        state = self.state
        if state == "closed":
            return state, None
        return state, (f"dispatch circuit breaker {state} "
                       f"({self._consecutive} consecutive failures)")


class QuarantineList:
    """Bounded set of quarantined (poison) query keys.

    Keys are normalized-query cache keys (tokenization + k-independent
    — one bad query is bad at every k), capped FIFO so a pathological
    traffic pattern cannot grow it unboundedly."""

    def __init__(self, cap: int = 1024, registry=None) -> None:
        if cap < 1:
            raise ValueError("cap must be >= 1")
        self.cap = cap
        self._lock = threading.Lock()
        self._keys: dict = {}            # key -> repr (insertion order)
        self._c_total = self._g_size = None
        if registry is not None:
            self._c_total = registry.counter(
                "serve_quarantined_total",
                "queries quarantined as poison")
            self._g_size = registry.gauge(
                "serve_quarantine_size",
                "currently quarantined query keys")

    def add(self, key, query_repr: str = "") -> bool:
        """Quarantine one key; returns False when already present."""
        with self._lock:
            if key in self._keys:
                return False
            if len(self._keys) >= self.cap:
                oldest = next(iter(self._keys))
                del self._keys[oldest]
            self._keys[key] = query_repr
            size = len(self._keys)
        if self._c_total is not None:
            self._c_total.inc()
        if self._g_size is not None:
            self._g_size.set(size)
        obs_log.log_event(
            "error", "query_quarantined",
            msg=f"query quarantined as poison ({size} total); "
                f"subsequent submissions fail fast with PoisonQuery",
            size=size)
        return True

    def contains(self, key) -> bool:
        with self._lock:
            return key in self._keys

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    def snapshot(self) -> List[str]:
        with self._lock:
            return [r if r else repr(k) for k, r in self._keys.items()]

    def clear(self) -> None:
        with self._lock:
            self._keys.clear()
        if self._g_size is not None:
            self._g_size.set(0)


def _match_text(queries: Sequence) -> str:
    """The device_dispatch seam's match surface: the batch's queries,
    NUL-joined (a fault rule's ``match=`` selects poison queries by
    substring)."""
    return "\x00".join(
        q.decode("utf-8", "replace") if isinstance(q, (bytes, bytearray))
        else str(q) for q in queries)


class SupervisedDispatch:
    """Wraps the batch search fn with retry, breaker and bisection.

    Args:
      search_fn: ``(queries, k, group) -> (vals, ids)`` — the same
        callable the bare :class:`~tfidf_tpu.serve.batcher.
        MicroBatcher` would call.
      policy: :class:`RetryPolicy` for transient failures.
      breaker: optional :class:`CircuitBreaker` recording every
        attempt outcome.
      metrics: optional :class:`~tfidf_tpu.serve.metrics.ServeMetrics`
        for the retry counter.
      retryable: predicate deciding whether an exception is transient
        (default: :class:`~tfidf_tpu.faults.TransientFault` only —
        real kernel errors are not blindly retried; widen it when a
        backend has known-transient error types).
    """

    def __init__(self, search_fn: Callable, policy: RetryPolicy,
                 breaker: Optional[CircuitBreaker] = None,
                 metrics=None,
                 retryable: Optional[Callable[[BaseException], bool]]
                 = None) -> None:
        self._search_fn = search_fn
        self.policy = policy
        self.breaker = breaker
        self._metrics = metrics
        self._retryable = retryable or (
            lambda e: isinstance(e, faults.TransientFault))
        self._rng = random.Random(policy.seed)

    # --- one dispatch with retry ---
    def run(self, queries: Sequence, k: int, group,
            batch_id: Optional[int] = None,
            rids: Optional[Sequence[str]] = None,
            first: Optional[Callable] = None
            ) -> Tuple[np.ndarray, np.ndarray]:
        """Dispatch with bounded retry on transient failures; raises
        the final error when the budget is exhausted or the failure is
        not retryable. The ``device_dispatch`` fault seam fires inside
        each attempt, so injected transients exercise this exact
        loop. ``rids`` (the batch's request ids, round 16) stamp the
        ``dispatch_retry`` spans and flight events so a retry's
        backoff is attributable to the requests that paid it.

        ``first`` (round 22) is the pipelined drain stage's seam: a
        zero-arg callable standing in for the FIRST attempt only —
        materializing a batch whose dispatch was already issued
        asynchronously (or re-raising its captured dispatch-stage
        error). The fault seam still fires inside that attempt, so
        kill/poison plans strike at drain time, exactly where a real
        deferred device failure surfaces; every RETRY re-dispatches
        synchronously through ``search_fn``. Attempt accounting,
        breaker story and retry counts are identical to the
        unpipelined path."""
        attempt = 0
        text = _match_text(queries)
        while True:
            attempt += 1
            if self.breaker is not None:
                # An open breaker pauses the attempt until half-open:
                # queued batches become the recovery probes instead of
                # hammering a failing device.
                wait = self.breaker.cooldown_remaining()
                if wait > 0:
                    time.sleep(wait)
            try:
                faults.fire("device_dispatch", text=text,
                            queries=len(queries), batch=batch_id)
                if first is not None and attempt == 1:
                    out = first()
                else:
                    out = self._search_fn(queries, k, group)
            except BaseException as e:  # noqa: BLE001 — classified below
                if self.breaker is not None:
                    self.breaker.record_failure()
                if (not self._retryable(e)
                        or attempt >= self.policy.max_attempts):
                    raise
                delay = faults.backoff_s(
                    attempt, self.policy.backoff_ms,
                    self.policy.backoff_mult,
                    self.policy.max_backoff_ms, self.policy.jitter,
                    self._rng)
                if self._metrics is not None:
                    self._metrics.count("dispatch_retries")
                extra = {"rids": list(rids)} if rids else {}
                obs_log.log_event(
                    "warning", "dispatch_retry",
                    msg=f"dispatch attempt {attempt} failed "
                        f"({type(e).__name__}); retrying in "
                        f"{delay * 1e3:.1f} ms",
                    attempt=attempt, batch=batch_id,
                    error=type(e).__name__, **extra)
                with obs.span("dispatch_retry", attempt=attempt,
                              batch=batch_id, **extra):
                    time.sleep(delay)
                continue
            if self.breaker is not None:
                self.breaker.record_success()
            return out

    # --- batch-level: retry then bisect ---
    def run_batch(self, queries: Sequence, k: int, group,
                  batch_id: Optional[int] = None,
                  rids: Optional[Sequence[str]] = None,
                  first: Optional[Callable] = None
                  ) -> Tuple[Optional[np.ndarray],
                             Optional[np.ndarray], List[int]]:
        """Dispatch the whole batch; on persistent failure, bisect to
        isolate the poison queries. Returns ``(vals, ids, poison)``:
        poison is the sorted list of query indices whose dispatch
        fails alone; every other row is the bit-identical result a
        clean dispatch would have produced. ``vals``/``ids`` are None
        only when EVERY query is poison.

        Bisection engages only for NON-retryable failures: a
        transient fault that survives the whole retry budget is
        overload/weather, not a poison query — the batch fails with
        the transient error (clients back off and retry) rather than
        quarantining innocent queries. Raises too when the full batch
        fails but no subset does (a non-separable failure).

        ``first`` rides through to :meth:`run`'s first attempt only
        (the pipelined drain materialization); bisection halves always
        re-dispatch synchronously — a poison query isolated at drain
        time bisects exactly like one isolated at dispatch time."""
        try:
            vals, ids = self.run(queries, k, group, batch_id,
                                 rids=rids, first=first)
            return np.asarray(vals), np.asarray(ids), []
        except BaseException as root:  # noqa: BLE001 — bisect below
            if self._retryable(root):
                raise       # retry budget exhausted on a transient
            if len(queries) == 1:
                self._log_poison([0], batch_id, root)
                return None, None, [0]
            results: dict = {}
            poison: List[int] = []
            mid = len(queries) // 2
            self._bisect(list(range(mid)), queries, k, group,
                         batch_id, results, poison, rids)
            self._bisect(list(range(mid, len(queries))), queries, k,
                         group, batch_id, results, poison, rids)
            if not poison:
                # Every subset passed but the whole batch failed — a
                # batch-shape-dependent fault, not a poison query.
                # One last full try; its error is the batch's error.
                vals, ids = self.run(queries, k, group, batch_id,
                                     rids=rids)
                return np.asarray(vals), np.asarray(ids), []
            self._log_poison(poison, batch_id, root)
            if len(results) == 0:
                return None, None, sorted(poison)
            some_v, some_i = next(iter(results.values()))
            vals = np.zeros((len(queries),) + some_v.shape,
                            some_v.dtype)
            ids = np.full((len(queries),) + some_i.shape, -1,
                          some_i.dtype)
            for i, (v, d) in results.items():
                vals[i], ids[i] = v, d
            return vals, ids, sorted(poison)

    def _bisect(self, idxs: List[int], queries, k, group, batch_id,
                results: dict, poison: List[int],
                rids: Optional[Sequence[str]] = None) -> None:
        if not idxs:
            return
        sub = [queries[i] for i in idxs]
        try:
            vals, ids = self.run(sub, k, group, batch_id, rids=rids)
        except BaseException as e:  # noqa: BLE001 — recurse or isolate
            if self._retryable(e):
                raise   # a transient storm mid-bisect aborts cleanly
            if len(idxs) == 1:
                poison.append(idxs[0])
                return
            mid = len(idxs) // 2
            self._bisect(idxs[:mid], queries, k, group, batch_id,
                         results, poison, rids)
            self._bisect(idxs[mid:], queries, k, group, batch_id,
                         results, poison, rids)
            return
        vals, ids = np.asarray(vals), np.asarray(ids)
        for j, i in enumerate(idxs):
            results[i] = (vals[j], ids[j])

    def _log_poison(self, poison: List[int], batch_id,
                    root: BaseException) -> None:
        obs_log.log_event(
            "error", "poison_isolated",
            msg=f"bisection isolated {len(poison)} poison "
                f"quer{'y' if len(poison) == 1 else 'ies'} in batch "
                f"{batch_id} ({type(root).__name__}); innocent "
                f"co-batched queries were served",
            batch=batch_id, poison=poison, error=type(root).__name__)
