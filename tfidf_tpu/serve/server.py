"""TfidfServer: the online query-serving front end over TfidfRetriever.

Composition (docs/SERVING.md has the full picture)::

    submit(queries, k, deadline) ── admission gate (queue_depth,
      Overloaded) ── per-query cache probe (epoch-keyed LRU) ── misses
      into the MicroBatcher ── coalesced TfidfRetriever.search on the
      epoch's index ── rows sliced per request, cache filled, Future
      resolved.

Guarantees:

* **Parity** — every response row is exactly what a direct
  ``TfidfRetriever.search`` of the same queries returns (batching,
  caching and concurrency never change bytes; pinned by
  tests/test_serve.py).
* **Bounded backlog** — at most ``queue_depth`` queries are admitted
  and unresolved at once; past that ``submit`` raises the typed
  :class:`Overloaded` instead of queueing unboundedly.
* **Deadlines** — a request still queued past its deadline is shed
  with :class:`DeadlineExceeded` before touching the device.
* **Hot swap** — :meth:`swap_index` atomically installs a new indexed
  retriever, bumps the epoch (cache keys include it) and clears the
  cache; requests already in flight finish on the index they were
  admitted under, so a streaming re-index goes live with zero
  downtime and zero mixed-epoch batches.
* **Graceful shutdown** — :meth:`close` drains in-flight work by
  default; ``drain=False`` fails queued requests fast.
* **Survival** (round 13) — device dispatch runs under a
  :class:`~tfidf_tpu.serve.supervisor.SupervisedDispatch` (bounded
  retry with jittered backoff for transient faults; poison-query
  bisection + quarantine — resubmitted poison fails fast with the
  typed :class:`PoisonQuery`); a :class:`~tfidf_tpu.serve.supervisor.
  CircuitBreaker` trips into degraded admission after N consecutive
  dispatch failures; the batcher loop restarts itself inside a
  budget; and :meth:`snapshot` / restore-on-start persist the
  resident index through ``checkpoint.py``'s crash-safe protocol so
  a killed server resumes serving without re-ingesting.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from tfidf_tpu import faults, obs
from tfidf_tpu.config import ServeConfig
from tfidf_tpu.models.retrieval import TfidfRetriever
from tfidf_tpu.obs import devmon as obs_devmon
from tfidf_tpu.obs import log as obs_log
from tfidf_tpu.obs import reqtrace
from tfidf_tpu.obs.health import HealthMonitor, HealthThresholds
from tfidf_tpu.obs.slo import SloTracker
from tfidf_tpu.serve.batcher import (DeadlineExceeded, MicroBatcher,
                                     Overloaded, PoisonQuery,
                                     ServeError, ServerClosed)
from tfidf_tpu.scoring.family import (parse_scorer, scorer_key,
                                      spec_from_parts)
from tfidf_tpu.scoring.filters import filter_key
from tfidf_tpu.serve.cache import ResultCache, normalize_query
from tfidf_tpu.serve.metrics import ServeMetrics
from tfidf_tpu.serve.supervisor import (CircuitBreaker, QuarantineList,
                                        RetryPolicy, SupervisedDispatch)

__all__ = ["TfidfServer", "ServeError", "Overloaded", "DeadlineExceeded",
           "ServerClosed", "PoisonQuery"]


class TfidfServer:
    """Serve ranked retrieval online. See module docstring.

    Args:
      retriever: an INDEXED :class:`TfidfRetriever` (the server never
        indexes; build/ingest stays the offline path).
      config: :class:`~tfidf_tpu.config.ServeConfig`; default reads
        the ``TFIDF_TPU_*`` env mirrors.
      metrics: optional shared :class:`ServeMetrics` sink.
    """

    def __init__(self, retriever: TfidfRetriever,
                 config: Optional[ServeConfig] = None,
                 metrics: Optional[ServeMetrics] = None,
                 initial_epoch: int = 0) -> None:
        if not retriever.indexed:
            raise ValueError("TfidfServer needs an indexed retriever; "
                             "call index()/index_dir() first")
        self.config = config or ServeConfig.from_env()
        self.metrics = metrics or ServeMetrics()
        # Mesh-sharded serving (round 18): with mesh_shards set, the
        # resident index is ONE logical index doc-sharded across the
        # chip mesh, and EVERY install path — this constructor, hot
        # swaps, mutation view installs — re-shards through the same
        # transform, so a swap or an add_docs can never quietly
        # install a single-device index into a sharded server.
        self._mesh_plan = None
        self._index_transform = None
        if self.config.mesh_shards is not None:
            from tfidf_tpu.parallel.serving import (make_serving_plan,
                                                    shard_index)
            self._mesh_plan = make_serving_plan(self.config.mesh_shards)
            plan = self._mesh_plan
            self._index_transform = lambda r: shard_index(r, plan)
            retriever = self._index_transform(retriever)
        self._apply_query_slab(retriever)
        self._retriever = retriever
        # initial_epoch: a snapshot-restored server resumes at the
        # epoch it snapshotted (cache keys and canary oracles stay
        # epoch-consistent across the restart).
        self._epoch = initial_epoch
        self._lock = threading.Lock()   # epoch/retriever swap + admission
        self._inflight = 0              # admitted, unresolved queries
        self._closed = False
        self._t0 = time.monotonic()     # uptime_s anchor
        self._swap_listeners: List[Callable] = []
        self._cache = ResultCache(self.config.cache_entries)
        # Default scorer (round 23): requests that name no scorer score
        # under this family member (--scorer / TFIDF_TPU_SCORER, with
        # --bm25-k1/--bm25-b fleshing out a bare "bm25"). Per-request
        # "scorer" fields override per batch group, never globally.
        self._default_scorer = spec_from_parts(
            self.config.scorer, self.config.bm25_k1, self.config.bm25_b)
        # Live mutation (round 17): an attached SegmentedIndex turns
        # add_docs/delete_docs on; every visibility change funnels
        # through _install_index (epoch bump + cache clear + listener
        # notify — the one path, so no mutation can leave a stale
        # cache row or an un-recaptured canary oracle behind).
        self._segments = None
        self._mutate_lock = threading.Lock()
        self._g_segments = self._g_delta_fill = self._g_tombstones = None
        # Fault plan (round 13): arming is the server's job when the
        # config names one (the chaos path — serve_bench --chaos /
        # TFIDF_TPU_FAULTS); disarmed again on close so an embedded
        # test server never leaks faults into the host process.
        self._armed_faults = None
        if self.config.faults:
            self._armed_faults = faults.arm(faults.FaultPlan.parse(
                self.config.faults, seed=self.config.fault_seed))
        # The health watchdog: batcher liveness + queue saturation +
        # windowed shed rates -> ok|degraded|unhealthy, with degraded
        # feeding back into admission (docstring of obs/health.py).
        # Always constructed (healthz/readyz evaluate on demand); the
        # background thread only runs when config.health_period_ms is
        # set (the serve CLI's default — library embedders opt in).
        self.health = HealthMonitor(
            snapshot_fn=self.metrics.snapshot,
            queue_bound=self.config.queue_depth,
            thresholds=HealthThresholds(
                stall_after_s=self.config.stall_after_ms / 1e3,
                degraded_admission_factor=(
                    self.config.degraded_admission_factor)),
            period_s=(self.config.health_period_ms / 1e3
                      if self.config.health_period_ms else 0.25),
            registry=self.metrics.registry)
        # Device truth (round 12): the compile watchdog ALWAYS watches
        # — steady-state serving promised zero recompiles after warmup
        # (round 9's pin), so any recompile past mark_warm() is a
        # flight event and a windowed degraded reason. The device
        # monitor runs when configured; its memory-pressure signal
        # sheds at the admission gate BEFORE the allocator OOMs, the
        # same feedback loop queue saturation already drives. The
        # watch is installed as THE process watch (latest server wins
        # — one serving process runs one server) and uninstalled on
        # close.
        self.compile_watch = obs_devmon.CompileWatch(
            registry=self.metrics.registry)
        obs_devmon.set_watch(self.compile_watch)
        self.health.add_signal("xla_recompiles_after_warm",
                               self.compile_watch.health_signal)
        self.devmon: Optional[obs_devmon.DeviceMonitor] = None
        if self.config.devmon_period_ms is not None:
            self.devmon = obs_devmon.DeviceMonitor(
                registry=self.metrics.registry,
                period_s=self.config.devmon_period_ms / 1e3)
            self.attach_device_monitor(self.devmon)
            self.devmon.start()
        # Supervised execution (round 13): retry/backoff + breaker +
        # poison bisection around the device call, and a supervised
        # (restartable) batcher loop. The breaker feeds health the
        # same way memory pressure does — open breaker -> degraded ->
        # admission bound shrinks at the gate.
        self.breaker = CircuitBreaker(
            threshold=self.config.breaker_threshold,
            cooldown_s=self.config.breaker_cooldown_ms / 1e3,
            registry=self.metrics.registry)
        self.health.add_signal("circuit_breaker",
                               self.breaker.health_signal)
        self.quarantine = QuarantineList(registry=self.metrics.registry)
        # Per-request forensics (round 16): slow-query threshold and
        # 1-in-N tail sample (obs/reqtrace.py), and the SLO burn
        # tracker (obs/slo.py) whose fast-burn signal degrades
        # admission exactly like memory pressure does — a server
        # blowing its latency objective sheds at the gate.
        self._slow_ms = self.config.slow_ms
        self._slow_sample = self.config.slow_sample
        self.slo: Optional[SloTracker] = None
        if self.config.slo_ms is not None:
            self.slo = SloTracker(
                objective_ms=self.config.slo_ms,
                target=self.config.slo_target,
                registry=self.metrics.registry)
            self.health.add_signal("slo_burn", self.slo.health_signal)
        self._dispatcher = SupervisedDispatch(
            self._run_batch,
            RetryPolicy(max_attempts=1 + self.config.dispatch_retries,
                        backoff_ms=self.config.retry_backoff_ms,
                        seed=self.config.fault_seed),
            breaker=self.breaker, metrics=self.metrics)
        self._batcher = MicroBatcher(
            self._run_batch, max_batch=self.config.max_batch,
            max_wait_ms=self.config.max_wait_ms, metrics=self.metrics,
            heartbeat=lambda: self.health.heartbeat("batcher"),
            supervisor=self._dispatcher,
            restart_budget=self.config.restart_budget,
            pipeline_depth=self.config.pipeline_depth,
            dispatch_fn=self._run_batch_async)
        self.health.register(
            "batcher",
            busy_fn=lambda: (self._batcher.queued_queries() > 0
                             or self._batcher.inflight_batches() > 0))
        if self.config.health_period_ms is not None:
            self.health.start()

    def _apply_query_slab(self, retriever) -> None:
        """Push the config's query-slab knob onto an (installable)
        index. Duck-typed: plain retrievers and segmented IndexViews
        that expose the attribute get it; mesh-sharded wrappers (no
        ``query_slab`` attr) keep their own staging contract. The
        pipeline depth rides along: with up to ``depth`` batches in
        flight, the slab pre-provisions that many slots per ring so
        the concurrent steady state stays allocation-free."""
        if (self.config.query_slab is not None
                and hasattr(retriever, "query_slab")):
            retriever.query_slab = self.config.query_slab
        if hasattr(retriever, "slab_depth"):
            retriever.slab_depth = self.config.pipeline_depth

    # --- the batch kernel the batcher drives ---
    def _run_batch(self, queries, k, group):
        epoch, retriever, skey, fkey = group
        if skey == "tfidf" and not fkey:
            # The bit-identical legacy call — also what keeps every
            # test-double retriever (2-arg search) working unchanged.
            return retriever.search(queries, k)
        return retriever.search(queries, k, scorer=skey,
                                filter=fkey or None)

    def _run_batch_async(self, queries, k, group):
        """Dispatch stage of the pipelined path: issue the device call
        and hand back a :class:`~tfidf_tpu.models.retrieval.
        PendingSearch` the drain worker materializes. Duck-typed so
        mesh-sharded and test-double retrievers without an async
        seam still pipeline (their search runs synchronously here;
        ordering and recovery semantics are unchanged)."""
        epoch, retriever, skey, fkey = group
        dispatch = getattr(retriever, "search_async", None)
        if dispatch is not None:
            if skey == "tfidf" and not fkey:
                return dispatch(queries, k)
            return dispatch(queries, k, scorer=skey,
                            filter=fkey or None)
        from tfidf_tpu.models.retrieval import PendingSearch
        return PendingSearch.resolved(
            *self._run_batch(queries, k, group))

    # --- public API ---
    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def num_docs(self) -> int:
        return self._retriever._num_docs

    def doc_names(self):
        return self._retriever.names

    def submit(self, queries: Sequence[Union[str, bytes]], k: int = 10,
               deadline_ms: Optional[float] = None, *,
               use_cache: bool = True, scorer=None,
               filter=None, trace: Optional[str] = None) -> Future:
        """Admit one request; returns a Future resolving to ``(vals,
        ids)`` — the exact arrays a direct ``retriever.search(queries,
        k)`` returns. Raises :class:`Overloaded` when the admission
        queue is full; the Future fails with
        :class:`DeadlineExceeded` when the deadline expires first.
        ``use_cache=False`` bypasses the result cache on both probe
        and fill — the canary prober's lever: its parity check must
        exercise the device path, not a memoized row.

        ``scorer``/``filter`` (round 23) select the scoring-family
        member and candidate filter for THIS request (any form
        ``tfidf_tpu.scoring`` parses; None = the server's default
        scorer, unfiltered). They canonicalize into the batch group —
        the batcher never coalesces requests that would score
        differently — and into the cache key, so a bm25 row can never
        answer a tfidf probe.

        The returned Future carries the request id as ``.rid`` (None
        with ``TFIDF_TPU_REQTRACE=off``) — the key that joins the
        JSONL response, the request's spans, its flight digest and
        any ``slow_query`` event (round 16).

        ``trace`` (round 23) adopts a front-minted fleet trace id
        (``t<16hex>``, :mod:`tfidf_tpu.obs.disttrace`) onto the
        request: the ``request`` span, the flight digest and the
        returned Future (``.trace``) all carry it next to the rid, so
        the front's ``route`` span and this replica's lifecycle chain
        join across processes. None = locally submitted."""
        t0 = time.monotonic()
        queries = list(queries)
        n = len(queries)
        # Canonicalize up front: a malformed spec is the submitter's
        # synchronous error, never a failed batch.
        skey = (scorer_key(scorer) if scorer is not None
                else self._default_scorer.key())
        fkey = filter_key(filter)
        # Request identity (round 16): minted at admission, carried on
        # the request through batcher -> cache -> supervisor -> device
        # dispatch -> drain, stamped on every span it touches.
        ctx = reqtrace.start(n, k, trace=trace)
        rid = ctx.rid if ctx is not None else None
        # The request lifecycle span: begun on the submitting thread,
        # ended (cross-thread) wherever the request resolves, with the
        # outcome as an arg — every submitted request appears exactly
        # once in a trace as drained / cache_hit / shed_* / error
        # (pinned by tests/test_obs.py).
        span_kw = {}
        if rid is not None:
            span_kw["rid"] = rid
        if trace is not None:
            span_kw["trace"] = trace
        req = obs.begin("request", queries=n, k=k, **span_kw)
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = None if deadline_ms is None else t0 + deadline_ms / 1e3
        # The EFFECTIVE admission bound: the configured queue_depth
        # while healthy, shrunk while the watchdog says degraded /
        # unhealthy — shedding earlier at the gate is how a degraded
        # server drains its backlog instead of compounding it.
        # Quarantine gate: a query isolated as poison by an earlier
        # batch's bisection fails fast here — the typed 4xx — instead
        # of re-poisoning a batch. Zero cost while the list is empty.
        if len(self.quarantine):
            qcfg = self._retriever.config
            bad = [q for q in queries
                   if self.quarantine.contains(normalize_query(q, qcfg))]
            if bad:
                self.metrics.count("poisoned")
                obs.end(req, outcome="poisoned")
                self._resolve_forensics(ctx, "poisoned")
                self._digest(t0, n, k, "poisoned", rid=rid)
                err = PoisonQuery(
                    f"{len(bad)} of {n} queries are quarantined as "
                    f"poison", queries=bad)
                err.rid = rid
                raise err
        bound = self.health.admission_bound(self.config.queue_depth)
        with self._lock:
            if self._closed:
                obs.end(req, outcome="rejected")
                raise ServerClosed("server is closed")
            if self._inflight + n > bound:
                self.metrics.count("shed_overload")
                obs.end(req, outcome="shed_overload")
                self._resolve_forensics(ctx, "shed_overload")
                self._digest(t0, n, k, "shed_overload", rid=rid)
                err = Overloaded(
                    f"{self._inflight} queries in flight + {n} exceeds "
                    f"admission bound {bound} (configured queue_depth="
                    f"{self.config.queue_depth})")
                err.rid = rid
                raise err
            self._inflight += n
            self.metrics.set_queue_depth(self._inflight)
            retriever, epoch = self._retriever, self._epoch
        cfg = retriever.config
        if ctx is not None:
            ctx.epoch = epoch

        out: Future = Future()
        out.rid = rid
        out.trace = trace
        # The ADMITTED epoch rides the future: a response's epoch is
        # decided here, never by a swap that lands mid-flight — the
        # per-request half of the replicated tier's no-mixed-epochs
        # contract (the JSONL protocol echoes it on every response).
        out.epoch = epoch
        if n == 0:
            width = min(k, retriever._num_docs)
            out.set_result((np.zeros((0, width), np.float32),
                            np.zeros((0, width), np.int64)))
            self.metrics.observe_request(time.monotonic() - t0, 0,
                                         rid=rid)
            obs.end(req, outcome="empty")
            self._resolve_forensics(ctx, "empty")
            return out

        if use_cache:
            t_cache = time.monotonic()
            keys = [self._cache.key(normalize_query(q, cfg), k, epoch,
                                    skey, fkey)
                    for q in queries]
            rows = [self._cache.get(key) for key in keys]
            hits = sum(r is not None for r in rows)
            if ctx is not None:
                ctx.mark("cache", time.monotonic() - t_cache)
            self.metrics.count("cache_hits", hits)
            self.metrics.count("cache_misses", n - hits)
        else:  # canary probes neither read nor skew the cache
            keys, rows, hits = [], [None] * n, 0
        miss_pos = [i for i, r in enumerate(rows) if r is None]

        def resolve(vals: np.ndarray, ids: np.ndarray,
                    outcome: str) -> None:
            self._finish(n)
            latency = time.monotonic() - t0
            self.metrics.observe_request(latency, n, rid=rid)
            if self.slo is not None:
                self.slo.record(latency)
            obs.end(req, outcome=outcome, cache_hits=hits)
            self._resolve_forensics(ctx, outcome)
            self._digest(t0, n, k, outcome, epoch=epoch,
                         cache_hits=hits, rid=rid)
            out.set_result((vals, ids))

        if not miss_pos:
            resolve(np.stack([r[0] for r in rows]),
                    np.stack([r[1] for r in rows]), "cache_hit")
            return out

        inner = self._batcher.submit([queries[i] for i in miss_pos], k,
                                     group=(epoch, retriever, skey,
                                            fkey),
                                     deadline=deadline, ctx=ctx)

        def on_done(f: Future) -> None:
            err = f.exception()
            if err is not None:
                self._finish(n)
                if isinstance(err, PoisonQuery):
                    # Bisection isolated poison queries in this
                    # request: quarantine them (resubmissions fail
                    # fast at the gate) and fail the future typed.
                    for q in err.queries:
                        self.quarantine.add(
                            normalize_query(q, cfg),
                            query_repr=f"len={len(q)}")
                    self.metrics.count("poisoned")
                    outcome = "poisoned"
                else:
                    outcome = (
                        "shed_deadline"
                        if isinstance(err, DeadlineExceeded)
                        else "shed_overload"
                        if isinstance(err, Overloaded)
                        else "error")
                obs.end(req, outcome=outcome)
                self._resolve_forensics(ctx, outcome)
                self._digest(t0, n, k, outcome, epoch=epoch,
                             error=(None if outcome != "error"
                                    else repr(err)), rid=rid)
                out.set_exception(err)
                return
            mvals, mids = f.result()
            if use_cache:
                for j, i in enumerate(miss_pos):
                    self._cache.put(keys[i], mvals[j], mids[j])
            if len(miss_pos) == n:
                resolve(mvals, mids, "drained")
                return
            vals = np.empty((n,) + mvals.shape[1:], mvals.dtype)
            ids = np.empty((n,) + mids.shape[1:], mids.dtype)
            for i, r in enumerate(rows):
                if r is not None:
                    vals[i], ids[i] = r
            for j, i in enumerate(miss_pos):
                vals[i], ids[i] = mvals[j], mids[j]
            resolve(vals, ids, "drained")

        inner.add_done_callback(on_done)
        return out

    def search(self, queries: Sequence[Union[str, bytes]], k: int = 10,
               timeout: Optional[float] = None, *, scorer=None,
               filter=None) -> Tuple[np.ndarray, np.ndarray]:
        """Blocking convenience wrapper over :meth:`submit`."""
        return self.submit(queries, k, scorer=scorer,
                           filter=filter).result(timeout=timeout)

    def default_scorer_key(self) -> str:
        """Canonical key of the scorer requests score under when they
        name none — what the canary prober captures its oracle with."""
        return self._default_scorer.key()

    def set_scorer(self, spec) -> int:
        """Change the server's DEFAULT scorer live (the ``set_scorer``
        JSONL op). Routed through :meth:`_install_index` — same
        retriever, but the epoch bumps, the result cache clears and
        the canary oracle re-captures under the new default, because a
        scorer change IS a visibility change: the same query now
        returns different bytes. Returns the new epoch."""
        parsed = parse_scorer(spec)
        with self._lock:
            retriever = self._retriever
            self._default_scorer = parsed
        return self._install_index(retriever, "scorer_change")

    def swap_index(self, retriever: TfidfRetriever) -> int:
        """Hot-swap the serving index: new submissions score against
        ``retriever`` immediately, in-flight requests finish on the
        index they were admitted under, and the result cache is
        invalidated (epoch bump + clear). Swap listeners (the canary
        prober's oracle re-capture) run synchronously BEFORE the epoch
        returns, so the swap is observable the instant it is live.
        Returns the new epoch.

        A swap racing :meth:`close` either completes or raises the
        typed :class:`ServerClosed` — never deadlocks (close never
        holds the admission lock while draining, and the snapshot /
        listeners here run outside it). With ``snapshot_dir``
        configured, the NEW epoch is snapshotted BEFORE the flip:
        a crash at any instant after the swap returns restores the
        index that was serving — the swap-then-crash hole is closed.
        """
        if not retriever.indexed:
            raise ValueError("swap_index needs an indexed retriever")
        faults.fire("swap", epoch=self._epoch + 1)
        if self.config.snapshot_dir:
            # Persist the incoming epoch first: if we crash between
            # here and the flip, the snapshot is merely ahead by one
            # swap that never went live — restoring it serves the
            # index the swap was installing, never a torn state.
            retriever.snapshot(self.config.snapshot_dir,
                               epoch=self._epoch + 1)
        # Swapping in an index that is NOT a view of the attached
        # segments detaches them: the full-rebuild fallback replaces
        # the segmented world wholesale, and further mutations must
        # say so instead of mutating a detached index nobody serves.
        with self._lock:
            if (self._segments is not None
                    and getattr(retriever, "owner", None)
                    is not self._segments):
                self._segments = None
                obs_log.log_event(
                    "warning", "index_swap",
                    msg="full-rebuild swap detached the segmented "
                        "index; add_docs/delete_docs now reject",
                    epoch=self._epoch + 1, reason="detach_segments")
        return self._install_index(retriever, "swap_index")

    def _install_index(self, retriever: TfidfRetriever,
                       reason: str) -> int:
        """THE visibility transition: atomically install ``retriever``
        (a plain retriever or a segmented :class:`~tfidf_tpu.index.
        IndexView`), bump the epoch, clear the epoch-keyed result
        cache and run the swap listeners (canary oracle re-capture)
        synchronously — every path that changes what a query could
        observe (swap, add, delete, seal, compaction install) funnels
        here, which is the no-stale-cache / no-false-canary contract
        tests/test_index.py pins. Under ``mesh_shards`` the incoming
        index is re-sharded across the mesh first (outside the
        admission lock — placement is slow; the flip stays atomic)."""
        if self._index_transform is not None:
            retriever = self._index_transform(retriever)
        self._apply_query_slab(retriever)
        with self._lock:
            if self._closed:
                raise ServerClosed("server is closed")
            self._retriever = retriever
            self._epoch += 1
            epoch = self._epoch
        self._cache.clear()
        if reason == "swap_index":
            obs_log.log_event(
                "info", "index_swap",
                msg=f"index swapped to epoch {epoch} "
                    f"({retriever._num_docs} docs)",
                epoch=epoch, docs=retriever._num_docs)
        else:
            obs_log.log_event(
                "info", "index_mutation",
                msg=f"index visibility -> epoch {epoch} "
                    f"({retriever._num_docs} docs, {reason})",
                epoch=epoch, docs=retriever._num_docs, reason=reason)
        for listener in list(self._swap_listeners):
            listener(epoch, retriever)
        return epoch

    # --- live mutation (round 17) ---
    def attach_segments(self, segments) -> None:
        """Wire a :class:`~tfidf_tpu.index.SegmentedIndex` into this
        server: :meth:`add_docs` / :meth:`delete_docs` /
        :meth:`compact_now` mutate it and install fresh views through
        :meth:`_install_index`, and the segment gauges
        (``serve_segment_count`` / ``serve_delta_fill_milli`` /
        ``serve_tombstones``) publish its shape."""
        reg = self.metrics.registry
        with self._lock:
            self._segments = segments
            if self._g_segments is None:
                self._g_segments = reg.gauge(
                    "serve_segment_count",
                    "segments serving (sealed + non-empty delta)")
                self._g_delta_fill = reg.gauge(
                    "serve_delta_fill_milli",
                    "delta-segment fill fraction in 1/1000")
                self._g_tombstones = reg.gauge(
                    "serve_tombstones",
                    "tombstoned (deleted/updated) rows awaiting "
                    "compaction")
        self._update_segment_gauges()

    def _segments_or_raise(self):
        with self._lock:
            segments = self._segments
        if segments is None:
            raise RuntimeError(
                "no segmented index attached (serve with --delta-docs, "
                "or TfidfServer.attach_segments)")
        return segments

    def _update_segment_gauges(self) -> None:
        with self._lock:
            segments, g_seg = self._segments, self._g_segments
        if segments is None or g_seg is None:
            return
        stats = segments.stats()
        g_seg.set(stats["segments"])
        self._g_delta_fill.set(int(round(stats["delta_fill"] * 1000)))
        self._g_tombstones.set(stats["tombstones"])

    def add_docs(self, names: Sequence[str],
                 docs: Sequence[Union[str, bytes]]) -> dict:
        """Add/update documents in the attached segmented index and
        make them visible: one mutation, one epoch bump, cache cleared,
        canary re-captured — all before this returns (visibility lag
        IS this call's latency; the mutate bench measures it)."""
        segments = self._segments_or_raise()
        with self._mutate_lock:
            summary = segments.add_docs(names, docs)
            epoch = self._install_index(segments.view(), "add_docs")
        self._update_segment_gauges()
        summary["epoch"] = epoch
        return summary

    def delete_docs(self, names: Sequence[str]) -> dict:
        """Tombstone documents by name. A delete that removed nothing
        installs nothing (no visibility change to publish)."""
        segments = self._segments_or_raise()
        with self._mutate_lock:
            summary = segments.delete_docs(names)
            if summary["deleted"]:
                summary["epoch"] = self._install_index(
                    segments.view(), "delete_docs")
            else:
                summary["epoch"] = self.epoch
        self._update_segment_gauges()
        return summary

    def compact_now(self, force: bool = False):
        """One threshold-checked compaction pass + view install — the
        :class:`~tfidf_tpu.index.Compactor`'s tick, also callable
        directly (tests, ops). Returns the compaction summary dict
        (with the installed epoch) or None when below threshold or
        when no segmented index is attached (a detached compactor tick
        is a no-op, not a crash)."""
        with self._lock:
            segments = self._segments
            if segments is None or self._closed:
                return None
        with self._mutate_lock:
            summary = segments.compact(force=force)
            if summary is None:
                return None
            try:
                summary["epoch"] = self._install_index(
                    segments.view(), "compaction")
            except ServerClosed:
                return None   # close raced the tick; nothing serves it
            if self.config.snapshot_dir:
                # Compaction is a durability point: the merged state
                # commits atomically, so a SIGKILL at any later
                # instant restores at worst the last compaction (plus
                # the boot/explicit-snapshot commits) — the classic
                # LSM trade of an unfsynced memtable tail.
                segments.save(self.config.snapshot_dir,
                              epoch=summary["epoch"])
        self._update_segment_gauges()
        return summary

    def snapshot(self, snapshot_dir: Optional[str] = None) -> str:
        """Persist the CURRENT resident index (CSR arrays + IDF +
        names + epoch + config fingerprint, checksummed) under
        ``snapshot_dir`` (default ``config.snapshot_dir``) through
        ``checkpoint.py``'s seq+LATEST atomic protocol. A process
        killed at any instant leaves the previous committed snapshot
        restorable; the serve CLI's ``--snapshot-dir`` restores it on
        start so a restarted server serves in seconds instead of
        re-ingesting. Returns the snapshot directory."""
        d = snapshot_dir or self.config.snapshot_dir
        if not d:
            raise ValueError("no snapshot dir (pass one or set "
                             "ServeConfig.snapshot_dir)")
        with self._lock:
            epoch, retriever = self._epoch, self._retriever
        t0 = time.monotonic()
        retriever.snapshot(d, epoch=epoch)
        obs_log.log_event(
            "info", "index_snapshot",
            msg=f"index snapshot (epoch {epoch}, "
                f"{retriever._num_docs} docs) -> {d} "
                f"in {time.monotonic() - t0:.3f}s",
            epoch=epoch, docs=retriever._num_docs, dir=d)
        return d

    def attach_device_monitor(self, monitor) -> None:
        """Wire a :class:`~tfidf_tpu.obs.devmon.DeviceMonitor` into
        this server: the resident index registers as a census owner
        (the registration reads ``self._retriever`` live, so a hot
        swap re-attributes automatically) and the monitor's memory
        pressure becomes a degraded health signal — high HBM shrinks
        the admission bound exactly like queue saturation does."""
        monitor.register_owner("resident_index", self._index_arrays)
        monitor.register_shards(self._shard_stats)
        self.health.add_signal("memory_pressure", monitor.health_signal)

    def _shard_stats(self):
        """Per-shard HBM balance of the CURRENT index (None when the
        resident index is not mesh-sharded) — the DeviceMonitor's
        ``shard_bytes_d*`` / ``shard_imbalance_milli`` gauge feed."""
        fn = getattr(self._retriever, "shard_stats", None)
        return fn() if fn is not None else None

    def mark_warm(self) -> None:
        """Declare serve warm-up complete: the compile watchdog flags
        every later fingerprinted compile as a steady-state recompile
        (flight event + windowed degraded reason). The serve CLI and
        tools/serve_bench.py call this after touching every
        power-of-two query bucket."""
        self.compile_watch.mark_warm()

    def _index_arrays(self):
        r = self._retriever
        if hasattr(r, "index_arrays"):   # segmented IndexView
            return r.index_arrays()
        return [r._ids, r._weights, r._head, r._idf]

    def add_swap_listener(self, fn: Callable) -> None:
        """Register ``fn(epoch, retriever)`` to run synchronously after
        every :meth:`swap_index` — how the canary prober re-captures
        its oracle at the only moment the new index is known-good."""
        self._swap_listeners.append(fn)

    def remove_swap_listener(self, fn: Callable) -> None:
        try:
            self._swap_listeners.remove(fn)
        except ValueError:
            pass

    def current_index(self) -> Tuple[int, TfidfRetriever]:
        """The (epoch, retriever) pair new submissions would score on."""
        with self._lock:
            return self._epoch, self._retriever

    def healthz(self) -> dict:
        """One watchdog evaluation, as the ``healthz`` op payload:
        typed status + reasons + raw checks + the effective admission
        bound (visibly below ``queue_depth`` while degraded)."""
        status = self.health.evaluate()
        out = status.as_dict()
        out["admission_bound"] = self.health.admission_bound(
            self.config.queue_depth)
        out["queue_depth"] = self.config.queue_depth
        out["uptime_s"] = round(time.monotonic() - self._t0, 3)
        return out

    def readyz(self) -> dict:
        """Readiness: serving is possible (indexed, not closed, not
        wedged). ``degraded`` stays ready — it still serves, just
        sheds earlier; ``unhealthy`` (a stalled worker) does not."""
        status = self.health.evaluate()
        ready = (not self._closed and self._retriever.indexed
                 and status.state != "unhealthy")
        return {"ready": ready, "status": status.state,
                "epoch": self._epoch}

    def fingerprint(self) -> dict:
        """Build/config identity for artifact provenance: a stable
        hash over the pipeline + serve configs plus corpus shape and
        backend — what makes a metrics snapshot self-describing in the
        perf ledger (two snapshots compare only if these match)."""
        import jax  # deferred; retriever already initialized a backend
        cfg = self._retriever.config
        ident = {
            "pipeline": {k: (v.value if hasattr(v, "value") else v)
                         for k, v in dataclasses.asdict(cfg).items()},
            "serve": dataclasses.asdict(self.config),
            "num_docs": self._retriever._num_docs,
            "backend": jax.default_backend(),
        }
        sha = hashlib.sha256(
            json.dumps(ident, sort_keys=True, default=str).encode()
        ).hexdigest()[:12]
        return {"config_sha": sha,
                "backend": ident["backend"],
                "num_docs": ident["num_docs"],
                "vocab_size": cfg.vocab_size}

    def metrics_snapshot(self, reset_peaks: bool = False) -> dict:
        """The ``metrics`` op / artifact snapshot: the pinned round-9
        ``ServeMetrics`` schema (tests assert a superset, guarding the
        ledger against silent renames) plus the self-describing keys —
        ``uptime_s``, current ``epoch`` and the build/config
        ``fingerprint`` — so a snapshot dropped into BENCH_LEDGER.jsonl
        still says what it measured."""
        snap = self.metrics.snapshot(reset_peaks=reset_peaks)
        snap["uptime_s"] = round(time.monotonic() - self._t0, 3)
        snap["epoch"] = self._epoch
        snap["fingerprint"] = self.fingerprint()
        # The SLO snapshot the serve CLI's ``metrics`` op promises:
        # windowed objective compliance + fast/slow burn rates when an
        # objective is configured (--slo-ms / TFIDF_TPU_SLO_MS), a
        # typed "not configured" marker otherwise — the key is always
        # present (pinned by tests/test_serve.py).
        snap["slo"] = (self.slo.snapshot() if self.slo is not None
                       else {"configured": False})
        return snap

    def metrics_prom(self) -> str:
        """Prometheus text exposition of the serve metrics (request
        latency histogram buckets included) — the ``metrics_prom``
        JSONL op and anything scraping a long-running server."""
        return self.metrics.render_prom()

    def obs_export(self) -> dict:
        """The cross-process federation bundle (``obs_export`` JSONL
        op): a versioned snapshot of this process's observability
        state — full registry instrument state (histogram buckets +
        exemplars, so :meth:`~tfidf_tpu.obs.registry.MetricsRegistry.
        merge` works losslessly on the receiving side), the recent
        flight-event tail and request digests, plus identity. This is
        what ``tools/obs_agg.py`` polls from N replicas and renders as
        one merged Prometheus/JSON view — the front-of-replicas
        aggregation of ROADMAP item 3, shipped ahead of the front."""
        if self.slo is not None:
            self.slo.snapshot()   # refresh the slo gauges pre-export
        log = obs_log.get_log()
        return {
            "schema": "tfidf-obs/1",
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "epoch": self._epoch,
            "fingerprint": self.fingerprint(),
            "registry": self.metrics.registry.export_state(),
            "flight_tail": log.events()[-64:],
            "digest_tail": log.digests()[-32:],
        }

    def close(self, drain: bool = True) -> None:
        """Stop admitting; ``drain=True`` serves the queued backlog
        before returning, ``drain=False`` fails it fast. Stops the
        health watchdog and — when a flight path is armed (``--flight``
        / ``TFIDF_TPU_FLIGHT``, or derived from an armed tracer) —
        dumps the flight recorder, so a clean shutdown leaves the same
        evidence a crash does. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._batcher.close(drain=drain)
        self.health.stop()
        if self.devmon is not None:
            self.devmon.stop()
        if obs_devmon.get_watch() is self.compile_watch:
            obs_devmon.set_watch(None)
        if self._armed_faults is not None:
            faults.disarm()
        obs_log.dump_flight()  # no-op unless a dump path is armed

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "TfidfServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    # --- internals ---
    def _finish(self, n: int) -> None:
        with self._lock:
            self._inflight -= n
            self.metrics.set_queue_depth(self._inflight)

    def _resolve_forensics(self, ctx, outcome: str) -> None:
        """Close one request's forensic record (obs/reqtrace.py): the
        phase breakdown resolves, and a request over the slow-query
        threshold (or the 1-in-N tail sample) emits its ``slow_query``
        flight event and bumps ``serve_slow_queries_total``."""
        tag = reqtrace.finish(ctx, outcome, slow_ms=self._slow_ms,
                              sample_every=self._slow_sample)
        if tag == "slow":
            self.metrics.count("slow_queries")

    def _digest(self, t0: float, n: int, k: int, outcome: str,
                epoch: Optional[int] = None,
                cache_hits: Optional[int] = None,
                error: Optional[str] = None,
                rid: Optional[str] = None) -> None:
        """One request digest into the flight recorder's last-N ring —
        sizes, outcome and latency, never query text (the dump may
        leave the machine). Cheap enough to record unconditionally.
        ``rid`` joins the digest to the request's spans and its JSONL
        response (round 16)."""
        rec = {"outcome": outcome, "queries": n, "k": k,
               "ms": round((time.monotonic() - t0) * 1e3, 3)}
        if rid is not None:
            rec["rid"] = rid
        if epoch is not None:
            rec["epoch"] = epoch
        if cache_hits:
            rec["cache_hits"] = cache_hits
        if error:
            rec["error"] = error
        obs_log.record_digest(**rec)
