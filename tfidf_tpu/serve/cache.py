"""LRU result cache keyed on normalized query tokens + k + index epoch.

Serving traffic is heavy-tailed: a small pool of hot queries covers a
large share of requests (the bench generator draws Zipf for exactly
this reason), so a per-query row cache turns the hot tail into zero
device work. Keys normalize through the SAME tokenizer the query
matrix uses (``ops.tokenize.whitespace_tokenize`` + the config's
truncation), so two spellings that score identically ("a  b" vs
"a b") share one entry — and a stale entry can never alias a fresh
one across :meth:`TfidfServer.swap_index`, because the index epoch is
part of the key (plus the server clears the cache outright on swap to
free the dead rows).

Values are the per-query ``(vals_row, ids_row)`` numpy pair exactly as
:meth:`TfidfRetriever.search` returned them — a cache hit is
bit-identical to recomputation by construction (search is
deterministic per query and independent of batch composition; pinned
by tests/test_serve.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Sequence, Tuple, Union

import numpy as np

from tfidf_tpu.config import PipelineConfig
from tfidf_tpu.ops.tokenize import whitespace_tokenize

Key = Tuple[Tuple[bytes, ...], int, int, str, str]
Row = Tuple[np.ndarray, np.ndarray]


def normalize_query(text: Union[str, bytes],
                    config: PipelineConfig) -> Tuple[bytes, ...]:
    """Canonical cache-key form of one query: its token tuple under the
    retriever's own tokenizer (truncation included), so key equality
    exactly matches scoring equality."""
    data = text.encode() if isinstance(text, str) else bytes(text)
    return tuple(whitespace_tokenize(data, config.truncate_tokens_at))


class ResultCache:
    """Thread-safe LRU over per-query result rows with hit/miss
    counters. ``entries == 0`` constructs a disabled cache (every
    lookup misses without counting; puts drop)."""

    def __init__(self, entries: int) -> None:
        if entries < 0:
            raise ValueError("entries must be >= 0")
        self.entries = entries
        self._lock = threading.Lock()
        self._rows: "OrderedDict[Key, Row]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    @property
    def enabled(self) -> bool:
        return self.entries > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    @staticmethod
    def key(tokens: Sequence[bytes], k: int, epoch: int,
            scorer: str = "tfidf", filter: str = "") -> Key:
        """``scorer``/``filter`` (round 23) are the CANONICAL keys
        (``scoring.scorer_key`` / ``scoring.filter_key``): two requests
        share an entry only when they would score identically — same
        tokens, same k, same epoch, same scorer-family member, same
        candidate set."""
        return (tuple(tokens), int(k), int(epoch), str(scorer),
                str(filter))

    def get(self, key: Key) -> Optional[Row]:
        if not self.enabled:
            return None
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                self.misses += 1
                return None
            self._rows.move_to_end(key)
            self.hits += 1
            return row

    def put(self, key: Key, vals_row: np.ndarray,
            ids_row: np.ndarray) -> None:
        if not self.enabled:
            return
        # Own copies: the cached row outlives the batch arrays it was
        # sliced from, and callers must never be able to mutate it.
        row = (np.array(vals_row, copy=True), np.array(ids_row, copy=True))
        row[0].setflags(write=False)
        row[1].setflags(write=False)
        with self._lock:
            self._rows[key] = row
            self._rows.move_to_end(key)
            while len(self._rows) > self.entries:
                self._rows.popitem(last=False)

    def clear(self) -> None:
        """Drop every entry (hot-swap invalidation); counters survive —
        they are lifetime serving stats, not per-epoch ones."""
        with self._lock:
            self._rows.clear()
