"""Online serving layer: micro-batching, caching, admission, SLOs.

The batch pipeline answers "index this corpus"; this package answers
"keep answering queries about it, forever, under concurrent load" —
the ROADMAP's serve-heavy-traffic leg. Four parts:

* :mod:`~tfidf_tpu.serve.batcher` — deadline-bounded dynamic
  micro-batching (submit queue -> futures -> coalesced device
  batches);
* :mod:`~tfidf_tpu.serve.cache` — epoch-keyed LRU result cache;
* :mod:`~tfidf_tpu.serve.server` — :class:`TfidfServer`: admission
  control, per-request deadlines, load shedding, hot index swap,
  graceful drain;
* :mod:`~tfidf_tpu.serve.metrics` — latency percentiles, batch
  occupancy, queue depth, shed/cache counters;
* :mod:`~tfidf_tpu.serve.canary` — background parity probes replaying
  pinned golden queries against the swap-time oracle, the live
  index-corruption detector (``serve_canary_parity`` gauge);
* :mod:`~tfidf_tpu.serve.front` — the replicated tier:
  :class:`ReplicatedFront` runs N full servers as worker processes
  behind one lightweight front (hash-affinity routing, two-phase
  epoch swaps, restart supervision, merged metrics);
* :mod:`~tfidf_tpu.serve.supervisor` — the recovery half: bounded
  retry with backoff for transient dispatch faults, a circuit breaker
  tripping into degraded admission, poison-query bisection +
  quarantine (typed :class:`PoisonQuery`), all rehearsable through
  the deterministic fault seams of :mod:`tfidf_tpu.faults`.

The server also watches itself: every :class:`TfidfServer` carries a
:class:`~tfidf_tpu.obs.health.HealthMonitor` deriving
``ok | degraded | unhealthy`` from worker heartbeats, queue
saturation and windowed shed rates (``healthz``/``readyz`` ops), with
``degraded`` shrinking the admission bound.

Entry points: the ``tfidf serve`` CLI subcommand (JSONL loop) and
``tools/serve_bench.py`` (load generator + ``SERVE_r0x.json``
artifact). docs/SERVING.md has the architecture notes;
docs/OBSERVABILITY.md the health/canary/flight-recorder story.
"""

from tfidf_tpu.serve.batcher import (DeadlineExceeded, MicroBatcher,
                                     Overloaded, PoisonQuery,
                                     ServeError, ServerClosed)
from tfidf_tpu.serve.cache import ResultCache, normalize_query
from tfidf_tpu.serve.canary import CanaryProber, pinned_queries_from_dir
from tfidf_tpu.serve.metrics import ServeMetrics
from tfidf_tpu.serve.server import TfidfServer
from tfidf_tpu.serve.supervisor import (CircuitBreaker, QuarantineList,
                                        RetryPolicy, SupervisedDispatch)
# front imports the submodules above; keep it LAST so the package
# namespace is fully populated before it loads.
from tfidf_tpu.serve.front import (FrontError, ReplicatedFront,
                                   SwapAborted)

__all__ = [
    "TfidfServer",
    "ReplicatedFront",
    "FrontError",
    "SwapAborted",
    "MicroBatcher",
    "ResultCache",
    "ServeMetrics",
    "CanaryProber",
    "ServeError",
    "Overloaded",
    "DeadlineExceeded",
    "ServerClosed",
    "PoisonQuery",
    "RetryPolicy",
    "CircuitBreaker",
    "QuarantineList",
    "SupervisedDispatch",
    "normalize_query",
    "pinned_queries_from_dir",
]
