"""Canary parity probes: catch a corrupted index WHILE serving it.

The serving layer's core guarantee is bit-parity — a served response
equals a direct ``TfidfRetriever.search`` of the same queries. The
test suite pins that at build time; nothing checked it in production,
where the failure mode that matters is SILENT index corruption after a
hot swap (a truncated segment, a miswired DF fold, a bad device
buffer). That detector is the prerequisite for the ROADMAP's riskier
index work (mesh sharding, LSM segments): you only mutate a live index
when something will notice a bad mutation before the postmortem does.

The prober is the serving twin of ``tfidf_tpu/golden.py``'s offline
oracle discipline: pin a small set of golden queries; capture their
ORACLE results by direct retriever search at index-build/swap time
(when the index is known-good — the same moment the swap's own parity
tests ran); then, forever after, periodically replay the pinned
queries through the FULL online path (admission → batcher → device
search, cache bypassed so the device actually scores) and bit-compare
against the captured oracle. The ``serve_canary_parity`` gauge is 1.0
while every probe matches; anything less is an alarm with the failing
query indices in the flight recorder.

Races are handled conservatively: a probe that straddles a hot swap
(epoch changed between submit and compare) or gets shed under load is
SKIPPED, not failed — the canary alarms only on evidence.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import numpy as np

from tfidf_tpu.obs import log as obs_log
from tfidf_tpu.serve.batcher import ServeError

__all__ = ["CanaryProber", "pinned_queries_from_dir"]


def pinned_queries_from_dir(input_dir: str, n: int = 8,
                            tokens: int = 4, strict: bool = True
                            ) -> List[str]:
    """Derive a pinned golden-query set from a corpus directory: the
    first ``tokens`` tokens of each of the first ``n`` documents (in
    the deterministic discovery order). Queries built from real doc
    prefixes are guaranteed to score nonzero against a healthy index,
    so a canary miss is signal, not vocabulary luck."""
    import os

    from tfidf_tpu.io.corpus import discover_names
    from tfidf_tpu.ops.tokenize import whitespace_tokenize
    queries: List[str] = []
    for name in discover_names(input_dir, strict=strict)[:n]:
        with open(os.path.join(input_dir, name), "rb") as f:
            data = f.read(4096)  # a prefix is plenty for `tokens` words
        toks = whitespace_tokenize(data)[:tokens]
        if toks:
            queries.append(b" ".join(toks).decode("utf-8", "replace"))
    return queries


class CanaryProber:
    """Replays pinned queries through the batched path and bit-compares
    against the swap-time oracle.

    Args:
      server: the :class:`~tfidf_tpu.serve.server.TfidfServer` to
        probe. The prober registers a swap listener so every
        ``swap_index`` re-captures the oracle synchronously — the
        capture happens inside the swap, before any post-swap
        corruption can exist.
      queries: the pinned golden queries (non-empty).
      k: results per query (one compiled bucket; probes never re-jit
        once warmed).
      period_s: background probe cadence for :meth:`start`; probes can
        also be driven manually (:meth:`probe` — the CLI ``canary``
        op).
      metrics: optional :class:`~tfidf_tpu.serve.metrics.ServeMetrics`
        (default: the server's) whose registry carries the
        ``serve_canary_parity`` gauge and probe/failure/skip counters.
    """

    def __init__(self, server, queries: Sequence[str], k: int = 10,
                 period_s: float = 1.0, metrics=None) -> None:
        queries = list(queries)
        if not queries:
            raise ValueError("canary needs at least one pinned query")
        if period_s <= 0:
            raise ValueError("period_s must be positive")
        self._server = server
        self._queries = queries
        self._k = k
        self.period_s = period_s
        m = metrics if metrics is not None else server.metrics
        reg = m.registry
        self._g_parity = reg.gauge(
            "serve_canary_parity_milli",
            "last canary probe parity vs swap-time oracle, in 1/1000 "
            "(1000 = every pinned query bit-identical)")
        self._c_probes = reg.counter(
            "serve_canary_probes_total", "canary probes compared")
        self._c_failures = reg.counter(
            "serve_canary_failures_total",
            "canary probes with any mismatched query")
        self._c_skipped = reg.counter(
            "serve_canary_skipped_total",
            "canary probes skipped (shed under load / swap race)")
        self._oracle: dict = {}           # epoch -> (vals, ids)
        self._lock = threading.Lock()
        self._parity: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        server.add_swap_listener(self._on_swap)
        self.capture()

    # --- oracle ---
    def _on_swap(self, epoch: int, retriever) -> None:
        self._capture(epoch, retriever)

    def capture(self) -> None:
        """(Re)capture the oracle for the server's CURRENT index."""
        epoch, retriever = self._server.current_index()
        self._capture(epoch, retriever)

    def _capture(self, epoch: int, retriever) -> None:
        # Direct search — the bit-parity reference the serve tests pin
        # served responses against; NOT through the batcher, so the
        # oracle is independent of the path under test. A mesh-sharded
        # index offers its retained SINGLE-DEVICE source as the oracle
        # (``parity_oracle``): probes then replay through the sharded
        # path and bit-compare against single-device search — the live
        # sharded-vs-single parity pin of ROADMAP item 1, not a
        # sharded-vs-itself tautology.
        oracle_fn = getattr(retriever, "parity_oracle", None)
        source = oracle_fn() if oracle_fn is not None else None
        src = source if source is not None else retriever
        # Per-scorer golden (round 23): the oracle captures under the
        # server's DEFAULT scorer — the one probes replay with — so the
        # parity pin holds under non-default scorers too. A scorer
        # change routes through ``_install_index`` (epoch bump + this
        # listener), so a stale-scorer oracle can never be compared:
        # the epoch check skips any probe that straddled the change.
        get_key = getattr(self._server, "default_scorer_key", None)
        skey = get_key() if get_key is not None else "tfidf"
        if skey != "tfidf":
            vals, ids = src.search(self._queries, self._k, scorer=skey)
        else:
            vals, ids = src.search(self._queries, self._k)
        with self._lock:
            self._oracle[epoch] = (np.asarray(vals), np.asarray(ids))
            # Keep the previous epoch for probes racing a swap; drop
            # anything older.
            for old in sorted(self._oracle)[:-2]:
                del self._oracle[old]

    # --- probing ---
    def probe(self, timeout: float = 30.0) -> Optional[float]:
        """One probe: submit the pinned queries through the full
        batched path (cache bypassed) and bit-compare with the oracle
        of the epoch the probe ran under. Returns the parity fraction
        in [0, 1], or None when the probe was skipped (shed under
        load, or a swap landed mid-flight). Updates the gauge and
        counters; mismatches log an ``error`` flight event carrying
        the failing query indices."""
        epoch = self._server.epoch
        try:
            fut = self._server.submit(self._queries, self._k,
                                      use_cache=False)
            vals, ids = fut.result(timeout=timeout)
        except ServeError:
            self._c_skipped.inc()
            return None
        if self._server.epoch != epoch:
            self._c_skipped.inc()       # swap raced the probe
            return None
        with self._lock:
            oracle = self._oracle.get(epoch)
        if oracle is None:              # capture raced; next probe wins
            self._c_skipped.inc()
            return None
        ovals, oids = oracle
        vals, ids = np.asarray(vals), np.asarray(ids)
        bad = [i for i in range(len(self._queries))
               if not (np.array_equal(vals[i], ovals[i])
                       and np.array_equal(ids[i], oids[i]))]
        parity = 1.0 - len(bad) / len(self._queries)
        with self._lock:
            # the `canary` op probes from a protocol thread while the
            # background prober runs its own cadence
            self._parity = parity
        self._c_probes.inc()
        self._g_parity.set(int(round(parity * 1000)))
        if bad:
            self._c_failures.inc()
            obs_log.log_event(
                "error", "canary_parity_failure",
                msg=f"canary: {len(bad)}/{len(self._queries)} pinned "
                    f"queries diverged from the epoch-{epoch} oracle "
                    f"(parity {parity:.3f}) — index corruption?",
                epoch=epoch, parity=round(parity, 4), queries=bad)
        return parity

    @property
    def parity(self) -> Optional[float]:
        """Parity of the last compared probe (None before the first)."""
        return self._parity

    # --- background prober ---
    def start(self) -> "CanaryProber":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.period_s):
                try:
                    self.probe()
                except Exception as e:  # noqa: BLE001 — prober must
                    # never kill serving; the failure IS the evidence.
                    obs_log.log_event("error", "canary_probe_error",
                                      msg=f"canary probe raised: {e}",
                                      error=repr(e))

        self._thread = threading.Thread(
            target=run, daemon=True, name="tfidf-serve-canary")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def close(self) -> None:
        self.stop()
        self._server.remove_swap_listener(self._on_swap)
