"""SLO metrics for the serving layer: latency, occupancy, shed/cache.

The batch pipeline's observability is per-run (``utils/timing.py``
phase walls); a server needs per-request distributions and counters
that survive millions of requests at O(1) memory. One
:class:`ServeMetrics` instance is shared by the server, batcher and
cache; every mutator takes the instance lock, so any thread can read a
consistent :meth:`snapshot` while traffic flows.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional

from tfidf_tpu.utils.timing import LatencyHistogram


class ServeMetrics:
    """Counters + latency histogram behind one lock.

    Tracked: request/query/batch counts, request latency (submit to
    resolution, :class:`~tfidf_tpu.utils.timing.LatencyHistogram`),
    batch occupancy (real queries / padded device-batch width — the
    coalescing efficiency), admission queue depth (current + peak),
    shed counters split by cause (overload vs deadline), and cache
    hit/miss counters.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.latency = LatencyHistogram()
        self._counts: Dict[str, int] = {
            "requests": 0, "queries": 0, "batches": 0,
            "shed_overload": 0, "shed_deadline": 0,
            "cache_hits": 0, "cache_misses": 0,
        }
        self._occupancy_sum = 0.0
        self._queue_depth = 0
        self._queue_peak = 0

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def observe_request(self, seconds: float, queries: int) -> None:
        with self._lock:
            self._counts["requests"] += 1
            self._counts["queries"] += queries
            self.latency.record(seconds)

    def observe_batch(self, real_queries: int, padded: int) -> None:
        with self._lock:
            self._counts["batches"] += 1
            self._occupancy_sum += real_queries / max(padded, 1)

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth
            self._queue_peak = max(self._queue_peak, depth)

    def snapshot(self) -> dict:
        """JSON-serializable point-in-time view (the artifact shape
        ``tools/serve_bench.py`` embeds and the CLI ``metrics`` op
        returns)."""
        with self._lock:
            c = dict(self._counts)
            batches = c.pop("batches")
            hits, misses = c.pop("cache_hits"), c.pop("cache_misses")
            lookups = hits + misses
            shed = c["shed_overload"] + c["shed_deadline"]
            return {
                "requests": c["requests"],
                "queries": c["queries"],
                "shed": {
                    "overload": c["shed_overload"],
                    "deadline": c["shed_deadline"],
                    "rate": round(shed / max(c["requests"] + shed, 1), 6),
                },
                "cache": {
                    "hits": hits,
                    "misses": misses,
                    "hit_rate": round(hits / lookups, 6) if lookups else 0.0,
                },
                "batch": {
                    "count": batches,
                    "mean_occupancy": round(
                        self._occupancy_sum / batches, 6) if batches else 0.0,
                },
                "queue": {"depth": self._queue_depth,
                          "peak": self._queue_peak},
                "latency_s": self.latency.as_dict(),
            }

    def render(self) -> str:
        """Human-readable text snapshot (stderr/ops form)."""
        s = self.snapshot()
        lat = s["latency_s"]
        return "\n".join([
            f"requests={s['requests']} queries={s['queries']} "
            f"shed={s['shed']['overload']}+{s['shed']['deadline']} "
            f"(rate {s['shed']['rate']:.3f})",
            f"latency p50={lat['p50'] * 1e3:.2f}ms "
            f"p95={lat['p95'] * 1e3:.2f}ms p99={lat['p99'] * 1e3:.2f}ms "
            f"mean={lat['mean'] * 1e3:.2f}ms n={lat['count']}",
            f"batches={s['batch']['count']} "
            f"occupancy={s['batch']['mean_occupancy']:.3f} "
            f"queue depth={s['queue']['depth']} peak={s['queue']['peak']}",
            f"cache hit_rate={s['cache']['hit_rate']:.3f} "
            f"({s['cache']['hits']}/{s['cache']['hits'] + s['cache']['misses']})",
        ])

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)
