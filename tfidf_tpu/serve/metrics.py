"""SLO metrics for the serving layer: latency, occupancy, shed/cache.

The batch pipeline's observability is per-run (``utils/timing.py``
phase walls); a server needs per-request distributions and counters
that survive millions of requests at O(1) memory. Since round 10 the
counters live on a unified :class:`~tfidf_tpu.obs.registry.
MetricsRegistry` instead of a private dict, which buys two things for
free: Prometheus text exposition (:meth:`ServeMetrics.render_prom`,
the CLI ``serve`` loop's ``metrics_prom`` op — request-latency
histogram buckets included) and resettable gauge peaks
(``snapshot(reset_peaks=True)`` restarts the queue-depth high-water
mark per snapshot window; the old private ``_queue_peak`` could never
reset, so a dashboard scraping every minute saw the all-time peak
forever).

One :class:`ServeMetrics` instance is shared by the server, batcher
and cache; instruments are individually locked, so any thread can
read a :meth:`snapshot` while traffic flows.
"""

from __future__ import annotations

import json
from typing import Optional

from tfidf_tpu.obs.registry import MetricsRegistry

_COUNTERS = {
    "requests": ("serve_requests_total", "requests resolved"),
    "queries": ("serve_queries_total", "queries resolved"),
    "batches": ("serve_batches_total", "coalesced device batches"),
    "shed_overload": ("serve_shed_overload_total",
                      "requests shed at admission (queue_depth)"),
    "shed_deadline": ("serve_shed_deadline_total",
                      "requests shed on an expired deadline"),
    "cache_hits": ("serve_cache_hits_total", "result-cache hits"),
    "cache_misses": ("serve_cache_misses_total", "result-cache misses"),
    "slow_queries": ("serve_slow_queries_total",
                     "requests over the slow-query threshold "
                     "(TFIDF_TPU_SLOW_MS)"),
}


class ServeMetrics:
    """Counters + latency histogram on one metrics registry.

    Tracked: request/query/batch counts, request latency (submit to
    resolution — a geometric-bucket histogram, O(1) memory), batch
    occupancy (real queries / padded device-batch width — the
    coalescing efficiency), admission queue depth (current + a
    resettable peak), shed counters split by cause (overload vs
    deadline), and cache hit/miss counters. :meth:`snapshot` keeps the
    exact JSON schema the round-9 artifacts pinned;
    :meth:`render_prom` is the new Prometheus face of the same data.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        self._counters = {
            short: self.registry.counter(name, help)
            for short, (name, help) in _COUNTERS.items()}
        self._occupancy = self.registry.counter(
            "serve_batch_occupancy_sum",
            "sum of per-batch occupancy (real/padded)")
        self._queue = self.registry.gauge(
            "serve_queue_depth", "admitted, unresolved queries")
        # exemplars=True: each latency bucket retains the LAST request
        # id that landed in it, exposed as OpenMetrics exemplars on
        # the Prometheus buckets and in the JSON snapshot — the link
        # from "p99 got worse" to one replayable trace (round 16).
        self._latency = self.registry.histogram(
            "serve_request_latency_seconds",
            "request latency, submit to resolution",
            exemplars=True)

    # Kept for callers that poke the histogram directly (the round-9
    # attribute name); the instrument's inner LatencyHistogram.
    @property
    def latency(self):
        return self._latency._h

    def count(self, name: str, n: int = 1) -> None:
        c = self._counters.get(name)
        if c is None:  # unknown names get ad-hoc registry counters
            c = self.registry.counter(f"serve_{name}_total", name)
            self._counters[name] = c
        c.inc(n)

    def observe_request(self, seconds: float, queries: int,
                        rid: Optional[str] = None) -> None:
        self._counters["requests"].inc()
        self._counters["queries"].inc(queries)
        self._latency.observe(seconds, exemplar=rid)

    def observe_batch(self, real_queries: int, padded: int) -> None:
        self._counters["batches"].inc()
        self._occupancy.inc(real_queries / max(padded, 1))

    def set_queue_depth(self, depth: int) -> None:
        self._queue.set(depth)

    def snapshot(self, reset_peaks: bool = False) -> dict:
        """JSON-serializable point-in-time view (the artifact shape
        ``tools/serve_bench.py`` embeds and the CLI ``metrics`` op
        returns). ``reset_peaks=True`` restarts the queue-depth peak
        at its current value AFTER reading, so each snapshot's peak
        covers only its own window."""
        c = {short: inst.value for short, inst in self._counters.items()}
        batches = c["batches"]
        hits, misses = c["cache_hits"], c["cache_misses"]
        lookups = hits + misses
        shed = c["shed_overload"] + c["shed_deadline"]
        occupancy = self._occupancy.value
        snap = {
            "requests": c["requests"],
            "queries": c["queries"],
            "shed": {
                "overload": c["shed_overload"],
                "deadline": c["shed_deadline"],
                "rate": round(shed / max(c["requests"] + shed, 1), 6),
            },
            "cache": {
                "hits": hits,
                "misses": misses,
                "hit_rate": round(hits / lookups, 6) if lookups else 0.0,
            },
            "batch": {
                "count": batches,
                "mean_occupancy": round(
                    occupancy / batches, 6) if batches else 0.0,
            },
            "queue": {"depth": self._queue.value,
                      "peak": self._queue.peak},
            "latency_s": self._latency.snapshot_value(),
            "slow_queries": c["slow_queries"],
        }
        if reset_peaks:
            self._queue.reset_peak()
        return snap

    def render(self) -> str:
        """Human-readable text snapshot (stderr/ops form)."""
        s = self.snapshot()
        lat = s["latency_s"]
        return "\n".join([
            f"requests={s['requests']} queries={s['queries']} "
            f"shed={s['shed']['overload']}+{s['shed']['deadline']} "
            f"(rate {s['shed']['rate']:.3f})",
            f"latency p50={lat['p50'] * 1e3:.2f}ms "
            f"p95={lat['p95'] * 1e3:.2f}ms p99={lat['p99'] * 1e3:.2f}ms "
            f"mean={lat['mean'] * 1e3:.2f}ms n={lat['count']}",
            f"batches={s['batch']['count']} "
            f"occupancy={s['batch']['mean_occupancy']:.3f} "
            f"queue depth={s['queue']['depth']} peak={s['queue']['peak']}",
            f"cache hit_rate={s['cache']['hit_rate']:.3f} "
            f"({s['cache']['hits']}/{s['cache']['hits'] + s['cache']['misses']})",
        ])

    def render_prom(self) -> str:
        """Prometheus text exposition of every serve instrument —
        request-latency ``le`` buckets, counters, queue gauge + peak.
        The ``serve`` CLI's ``metrics_prom`` op returns this."""
        return self.registry.render_prom()

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)
