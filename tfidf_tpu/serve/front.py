"""Replicated serving tier: N replica processes behind one front.

The reference's whole design is rank-parallel throughput
(``TFIDF.c:130``'s rank-partitioned document loop); this module is
the serving-side counterpart: one lightweight FRONT owns the client
protocol and routes queries across N worker processes, each a full
:class:`~tfidf_tpu.serve.server.TfidfServer` owning its own device
link, spun up from the shared ``--snapshot-dir`` via the
``launch_rank`` star process model (``parallel/multihost.py``) — the
same framed mpi_lite channels the sharded-ingest workers speak.

Two planes per replica:

* **data plane** — JSONL over the child's stdin/stdout, the exact
  ``tfidf serve`` protocol (``cli._serve_handle_line``): queries,
  health, obs_export. Responses are matched by wire id, so the
  completion-order protocol survives the hop.
* **control plane** — framed mpi_lite messages (tags ``_CTRL`` /
  ``_CTRL_ACK``) carrying the two-phase epoch protocol. Control is
  strictly one-outstanding-per-replica (serialized under the front's
  swap lock), so the per-channel ordering the wire protocol pins is
  preserved by construction.

Routing is hash-by-normalized-query — shared-nothing result caches
make affinity the whole ballgame — with a least-loaded fallback when
the preferred replica is degraded (its own watchdog's ``healthz``
verdict, polled by the front) or dead. On replica death the front
re-routes that replica's in-flight idempotent requests to survivors
and respawns the child from the shared snapshot under the
``restart_budget`` supervision the batcher already honors.

Index visibility changes (``swap_index``, ``add_docs`` /
``delete_docs``, compaction installs) are a **two-phase epoch bump**:

1. ``prepare`` on every live replica — stage the change (build the
   incoming index, validate the mutation), touching nothing visible;
2. a ``ping`` round — a replica that acked prepare and then died
   (the SIGKILL-between-phases chaos pin) is caught HERE, before any
   replica has installed anything, and the transaction aborts with
   the tier still serving the old epoch everywhere;
3. admission gate closes (new queries wait at the front), ``commit``
   fans out writer-first — the lowest live rank applies, snapshots
   the NEW epoch to the shared dir, then the rest apply — and the
   gate reopens.

In-flight queries admitted before the gate carry their admitted epoch
end to end (the server pins ``(epoch, retriever)`` at admission and
the response line echoes ``epoch``), so no response ever straddles a
swap. True simultaneous cross-process commit is impossible (the two
generals' residue): a replica killed *during* the commit fan-out may
briefly disagree, and the front heals it by restarting the replica
from the writer's snapshot and re-snapshotting from a live peer until
the epochs agree — docs/SERVING.md walks the failure story.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

from tfidf_tpu.obs import disttrace
from tfidf_tpu.parallel.multihost import (MpiLiteComm, MpiLiteError,
                                          launch_rank)

__all__ = ["ReplicatedFront", "FrontError", "SwapAborted"]

# Control-plane tags: point-to-point tags are >= 0 in the mpi_lite
# protocol; these share the channel with nothing else (the front's
# swap lock serializes control traffic).
_CTRL = 11
_CTRL_ACK = 12

_OBS_SCHEMA = "tfidf-obs/1"
#: Fleet trace-export bundle schema (round 23): one entry per process
#: — its Chrome events verbatim plus the identity/clock metadata
#: tools/trace_merge.py aligns lanes with.
_TRACE_SCHEMA = "tfidf-trace/1"
#: Round trips per clock-offset handshake. Min-RTT filtering over 8
#: samples bounds the offset error by half the best observed pipe RTT
#: (tens of µs on a local socketpair) — far under any span we render.
_CLOCK_SAMPLES = 8

#: env the replicas must NOT inherit: trace/flight paths would have N
#: processes clobbering one file, and a leaked TFIDF_TPU_REPLICAS
#: must never make a replica try to spawn a tier of its own.
_STRIP_ENV = ("TFIDF_TPU_TRACE", "TFIDF_TPU_FLIGHT",
              "TFIDF_TPU_REPLICAS", "TFIDF_TPU_FAULTS")


class FrontError(RuntimeError):
    """The front could not complete a request (no live replicas, a
    replica unreachable past its timeout, a refused mutation)."""


class SwapAborted(FrontError):
    """A two-phase epoch transaction aborted before any replica
    installed it — the tier is still serving the OLD epoch everywhere
    (the invariant the chaos kill-mid-swap rehearsal pins)."""


class _Pending:
    """One forwarded request awaiting its response line."""

    __slots__ = ("req", "rank", "boot", "event", "response",
                 "retryable")

    def __init__(self, req: dict, retryable: bool):
        self.req = req
        self.rank = -1
        self.boot = -1
        self.event = threading.Event()
        self.response: Optional[dict] = None
        self.retryable = retryable


class _Replica:
    """Front-side handle for one replica process."""

    __slots__ = ("rank", "proc", "boot", "state", "epoch", "routed",
                 "inflight", "restarts", "health", "ready_evt",
                 "ready_info", "wlock", "num_docs", "pid")

    def __init__(self, rank: int):
        self.rank = rank
        self.proc: Optional[subprocess.Popen] = None
        self.boot = -1
        # down | starting | live | dead | failed | stopping
        self.state = "down"
        self.epoch = 0
        self.routed = 0
        self.inflight = 0
        self.restarts = 0
        self.health = "ok"
        self.ready_evt: Optional[threading.Event] = None
        self.ready_info: Optional[dict] = None
        self.wlock = threading.Lock()   # stdin line-atomicity
        self.num_docs = 0
        self.pid: Optional[int] = None


class ReplicatedFront:
    """The tier: spawn N replicas from a shared snapshot, route
    queries, supervise restarts, drive two-phase epoch swaps, and
    merge the fleet's observability into one view.

    ``serve_cfg.replicas`` is N and ``serve_cfg.snapshot_dir`` is the
    shared checkpoint root (both required). The pipeline config and
    ``input_dir`` are what replica 1 bootstraps the index from when
    the snapshot root is empty; every other boot restores.
    """

    def __init__(self, input_dir: Optional[str], pipeline_cfg,
                 serve_cfg, *, k: int = 10, no_strict: bool = False,
                 doc_len: Optional[int] = None):
        if not serve_cfg.replicas:
            raise ValueError("ReplicatedFront needs "
                             "ServeConfig.replicas >= 1")
        self._input_dir = input_dir
        self._pipeline_cfg = pipeline_cfg
        self._serve_cfg = serve_cfg
        self._n = int(serve_cfg.replicas)
        self._size = self._n + 1          # rank 0 = the front
        self._k = k
        self._no_strict = no_strict
        self._doc_len = doc_len
        self._comm = MpiLiteComm(0, self._size, [-1] * self._size)
        self._replicas: Dict[int, _Replica] = {
            r: _Replica(r) for r in range(1, self._size)}
        self._lock = threading.Lock()        # routing / pending state
        self._swap_lock = threading.Lock()   # mutations + restarts
        self._admission = threading.Event()  # closed during commits
        self._admission.set()
        self._pending: Dict[int, _Pending] = {}
        self._ids = itertools.count(1)
        self._txns = itertools.count(1)
        self._epoch = 0
        self._closed = False
        self._started = False
        self._t0 = time.monotonic()
        self._specs_dir = tempfile.mkdtemp(prefix="tfidf_front_")
        self._restart_q: "queue.Queue[Optional[int]]" = queue.Queue()
        # Fleet tracing (round 23): ServeConfig.disttrace overrides
        # the env default for this process AND (via the spec) every
        # replica; per-replica clock-offset estimators feed the
        # trace-export metadata tools/trace_merge.py aligns with.
        if serve_cfg.disttrace is not None:
            disttrace.configure(serve_cfg.disttrace)
        self._clocks: Dict[int, disttrace.ClockOffsetEstimator] = {
            r: disttrace.ClockOffsetEstimator()
            for r in range(1, self._size)}

        from tfidf_tpu.obs.registry import MetricsRegistry
        self._registry = MetricsRegistry()
        self._m_routed = self._registry.counter(
            "serve_front_routed_total",
            "query requests the front routed to a replica")
        self._m_rerouted = self._registry.counter(
            "serve_front_rerouted_total",
            "in-flight requests re-routed off a dead replica")
        self._m_fallbacks = self._registry.counter(
            "serve_front_route_fallbacks_total",
            "routes that left the hash-preferred replica "
            "(degraded/dead) for the least-loaded one")
        self._m_restarts = self._registry.counter(
            "serve_front_replica_restarts_total",
            "replica processes respawned by the front")
        self._m_commits = self._registry.counter(
            "serve_front_epoch_commits_total",
            "two-phase epoch transactions committed tier-wide")
        self._m_aborts = self._registry.counter(
            "serve_front_epoch_aborts_total",
            "two-phase epoch transactions aborted (tier stayed on "
            "the old epoch)")
        self._m_live = self._registry.gauge(
            "serve_front_replicas_live", "replicas currently serving")

    # --- lifecycle ---------------------------------------------------

    def start(self) -> "ReplicatedFront":
        """Bootstrap the tier: replica 1 first (it builds + snapshots
        when the snapshot root is empty), then the rest restore from
        the snapshot concurrently."""
        if self._started:
            return self
        from tfidf_tpu import obs
        obs.set_export_meta(process="front",
                            clock={"offset_ns": 0, "uncertainty_ns": 0,
                                   "rtt_ns": 0, "samples": 0})
        self._spawn(1, bootstrap=True)
        self._await_ready(1)
        self._sync_clock(1)
        for rank in range(2, self._size):
            self._spawn(rank, bootstrap=False)
        for rank in range(2, self._size):
            self._await_ready(rank)
            self._sync_clock(rank)
        with self._lock:
            epochs = {r: rep.epoch for r, rep in self._replicas.items()}
        if len(set(epochs.values())) != 1:
            self.close()
            raise FrontError(f"replicas booted on mixed epochs: "
                             f"{epochs}")
        self._epoch = epochs[1]
        threading.Thread(target=self._supervise, daemon=True,
                         name="front-supervisor").start()
        threading.Thread(target=self._health_loop, daemon=True,
                         name="front-health").start()
        self._started = True
        return self

    def _spec_for(self, rank: int, boot: int, bootstrap: bool) -> str:
        import dataclasses

        from tfidf_tpu.parallel.multihost import _config_to_spec
        serve_kw = dataclasses.asdict(self._serve_cfg)
        # The replica's server must never snapshot on its own (swaps
        # would race N writers into one dir) and must never try to
        # build a tier of its own.
        serve_kw["snapshot_dir"] = None
        serve_kw["replicas"] = None
        spec = {
            "rank": rank, "boot": boot, "bootstrap": bool(bootstrap),
            # Front-resolved fleet-tracing verdict: a replica inherits
            # no TFIDF_TPU_TRACE (see _STRIP_ENV) — this flag arms its
            # IN-MEMORY span ring instead, pulled over the data plane
            # by the trace_export op.
            "disttrace": disttrace.enabled(),
            "snapshot_dir": self._serve_cfg.snapshot_dir,
            "input_dir": self._input_dir,
            "k": self._k, "no_strict": self._no_strict,
            "doc_len": self._doc_len,
            "pipeline": _config_to_spec(self._pipeline_cfg),
            "serve": serve_kw,
        }
        path = os.path.join(self._specs_dir,
                            f"replica_{rank}_b{boot}.json")
        with open(path, "w") as f:
            json.dump(spec, f)
        return path

    def _spawn(self, rank: int, bootstrap: bool) -> None:
        rep = self._replicas[rank]
        boot = rep.boot + 1
        spec_path = self._spec_for(rank, boot, bootstrap)
        env = dict(os.environ)
        for var in _STRIP_ENV:
            env.pop(var, None)
        # Replicas import this package by module path; make sure they
        # can even when the front was launched from elsewhere.
        import tfidf_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(tfidf_tpu.__file__)))
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join(
                [pkg_root] + [p for p in parts if p])
        # stderr inherited: replicas log there, and an undrained pipe
        # would wedge a chatty child on the 64 KiB pipe buffer.
        # -c, not -m: runpy would import the package (which imports
        # this module) and then execute this module AGAIN as __main__.
        fd, proc = launch_rank(
            rank, self._size,
            [sys.executable, "-c",
             "import sys\n"
             "from tfidf_tpu.serve.front import _replica_main\n"
             "sys.exit(_replica_main(sys.argv[1]))", spec_path],
            env=env, stderr=None)
        with self._lock:
            rep.proc = proc
            rep.boot = boot
            rep.state = "starting"
            rep.ready_evt = threading.Event()
            rep.ready_info = None
        self._comm.wire(rank, fd)
        threading.Thread(target=self._reader, args=(rank, proc, boot),
                         daemon=True,
                         name=f"front-reader-r{rank}").start()

    def _await_ready(self, rank: int) -> None:
        rep = self._replicas[rank]
        evt = rep.ready_evt
        timeout = self._serve_cfg.replica_timeout_s
        if not evt.wait(timeout):
            self._kill(rank)
            raise FrontError(f"replica {rank} not ready within "
                             f"{timeout:.0f}s")
        with self._lock:
            info = rep.ready_info
            if info is None:     # died during boot
                raise FrontError(f"replica {rank} died during boot")
            rep.state = "live"
            rep.epoch = int(info.get("epoch", 0))
            rep.num_docs = int(info.get("num_docs", 0))
            rep.pid = info.get("pid")
            rep.health = "ok"
            live = sum(1 for r in self._replicas.values()
                       if r.state == "live")
        self._m_live.set(live)
        from tfidf_tpu.obs import log as obs_log
        obs_log.log_event(
            "info", "replica_up",
            msg=f"replica {rank} up (boot {rep.boot}, epoch "
                f"{rep.epoch}, {rep.num_docs} docs, pid {rep.pid})",
            replica=rank, boot=rep.boot, epoch=rep.epoch,
            docs=rep.num_docs, pid=rep.pid)

    def _sync_clock(self, rank: int) -> None:
        """Clock-offset handshake with one replica over the ctrl plane
        (serialized like every ctrl op — called at boot, before the
        supervisor threads exist, and from _restart under the swap
        lock): N ``clock_sync`` round trips, RTT-midpoint estimate,
        min-RTT filter (obs/disttrace.py). The estimate lands in the
        trace-export METADATA — captured timestamps are never
        rewritten, so a bad estimate is re-appliable, not baked in.
        Always re-estimated from scratch: a restarted replica is a new
        process and a new ``perf_counter`` epoch."""
        if not disttrace.enabled():
            return
        est = self._clocks[rank]
        est.reset()
        for _ in range(_CLOCK_SAMPLES):
            t_send = time.perf_counter_ns()
            try:
                ack = self._ctrl_rpc(rank, {"op": "clock_sync"},
                                     timeout_s=10.0)
            except FrontError:
                return     # supervision handles the death; no estimate
            t_recv = time.perf_counter_ns()
            t_peer = ack.get("t_ns")
            if ack.get("ok") and isinstance(t_peer, int):
                est.add_sample(t_send, t_peer, t_recv)
        from tfidf_tpu.obs import log as obs_log
        rep = self._replicas[rank]
        obs_log.log_event(
            "info", "clock_sync",
            msg=(f"replica {rank} clock offset "
                 f"{(est.offset_ns or 0) / 1e3:.1f} µs "
                 f"± {(est.uncertainty_ns or 0) / 1e3:.1f} µs "
                 f"({est.n_samples} samples, boot {rep.boot})"),
            replica=rank, boot=rep.boot, offset_ns=est.offset_ns,
            uncertainty_ns=est.uncertainty_ns, rtt_ns=est.rtt_ns,
            samples=est.n_samples)

    def _kill(self, rank: int) -> None:
        proc = self._replicas[rank].proc
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass

    def close(self) -> None:
        """Drain and stop every replica; emits the final per-replica
        ``replica_down`` accounting the doctor's routed-share view
        reads."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for rep in self._replicas.values():
                if rep.state in ("live", "starting"):
                    rep.state = "stopping"
        self._restart_q.put(None)
        # No mutation may be mid-commit while we pull stdin out from
        # under the replicas.
        with self._swap_lock:
            pass
        from tfidf_tpu.obs import log as obs_log
        for rank, rep in sorted(self._replicas.items()):
            proc = rep.proc
            if proc is not None and proc.poll() is None:
                try:
                    with rep.wlock:
                        if proc.stdin is not None:
                            proc.stdin.close()
                except OSError:
                    pass
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    self._kill(rank)
                    try:
                        proc.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        pass
            obs_log.log_event(
                "info", "replica_down",
                msg=f"replica {rank} shut down ({rep.routed} requests "
                    f"routed, {rep.restarts} restarts)",
                replica=rank, boot=rep.boot, reason="shutdown",
                routed=rep.routed, restarts=rep.restarts)
        self._m_live.set(0)
        self._comm.close()
        shutil.rmtree(self._specs_dir, ignore_errors=True)

    def __enter__(self) -> "ReplicatedFront":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- introspection -----------------------------------------------

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def n_replicas(self) -> int:
        return self._n

    def _live_ranks(self) -> List[int]:
        with self._lock:
            return sorted(r for r, rep in self._replicas.items()
                          if rep.state == "live")

    def describe(self) -> dict:
        """Per-replica liveness/routing/restart state — the front's
        half of ``healthz`` and the doctor's replicas section."""
        with self._lock:
            reps = {
                str(r): {
                    "state": rep.state, "health": rep.health,
                    "epoch": rep.epoch, "boot": rep.boot,
                    "routed": rep.routed, "inflight": rep.inflight,
                    "restarts": rep.restarts, "pid": rep.pid,
                }
                for r, rep in sorted(self._replicas.items())}
        live = sum(1 for r in reps.values() if r["state"] == "live")
        status = ("ok" if live == self._n
                  else "degraded" if live else "unhealthy")
        return {"status": status, "epoch": self._epoch,
                "replicas": reps, "n_replicas": self._n,
                "live": live,
                "admission_open": self._admission.is_set(),
                "uptime_s": round(time.monotonic() - self._t0, 3)}

    # --- data plane --------------------------------------------------

    def _reader(self, rank: int, proc: subprocess.Popen,
                boot: int) -> None:
        """One thread per replica process: pump its stdout, resolve
        pending requests by wire id, and on EOF declare the replica
        dead (re-route + restart)."""
        try:
            for raw in proc.stdout:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    obj = json.loads(raw)
                except ValueError:
                    continue      # stray non-protocol output
                if not isinstance(obj, dict):
                    continue
                if obj.get("ready"):
                    with self._lock:
                        rep = self._replicas[rank]
                        if rep.boot != boot:
                            continue
                        rep.ready_info = obj
                        evt = rep.ready_evt
                    evt.set()
                    continue
                wire_id = obj.get("id")
                pend = None
                with self._lock:
                    rep = self._replicas[rank]
                    if wire_id is not None:
                        pend = self._pending.pop(wire_id, None)
                    if rep.boot == boot and rep.inflight > 0:
                        rep.inflight -= 1
                if pend is not None:
                    pend.response = obj
                    pend.event.set()
        except (OSError, ValueError):
            pass
        self._on_replica_death(rank, boot)

    def _on_replica_death(self, rank: int, boot: int) -> None:
        with self._lock:
            rep = self._replicas[rank]
            if rep.boot != boot or rep.state in ("stopping", "down",
                                                 "dead", "failed"):
                if rep.state == "stopping":
                    rep.state = "down"
                return
            was_starting = rep.state == "starting"
            rep.state = "dead"
            rep.health = "unknown"
            rep.inflight = 0
            routed = rep.routed
            evt = rep.ready_evt
            mine = [(i, p) for i, p in self._pending.items()
                    if p.rank == rank and p.boot == boot]
            for i, _ in mine:
                self._pending.pop(i, None)
            live = sum(1 for r in self._replicas.values()
                       if r.state == "live")
            closed = self._closed
        self._comm.unwire(rank)
        self._m_live.set(live)
        if was_starting and evt is not None:
            evt.set()     # unblock _await_ready with ready_info=None
        from tfidf_tpu.obs import log as obs_log
        obs_log.log_event(
            "warning", "replica_down",
            msg=f"replica {rank} died (boot {boot}, {routed} requests "
                f"routed, {len(mine)} in flight)",
            replica=rank, boot=boot, reason="died", routed=routed,
            inflight=len(mine))
        if not closed:
            for _, pend in mine:
                if pend.retryable:
                    try:
                        target = self._pick(self._norm_for(pend.req))
                        self._submit_to(target, pend.req, pend=pend)
                        self._m_rerouted.inc()
                        continue
                    except FrontError:
                        pass
                pend.response = {"error": f"replica {rank} died"}
                pend.event.set()
            self._restart_q.put(rank)
        else:
            for _, pend in mine:
                pend.response = {"error": "front is closing"}
                pend.event.set()

    def _norm_for(self, req: dict) -> bytes:
        from tfidf_tpu.serve.cache import normalize_query
        queries = req.get("queries") or [""]
        q = queries[0] if isinstance(queries, list) and queries else ""
        try:
            # The cache key's own token tuple — routing affinity is
            # exactly cache-hit affinity.
            return b"\x00".join(normalize_query(q,
                                                self._pipeline_cfg))
        except (TypeError, ValueError, AttributeError):
            return str(q).encode("utf-8", "replace")

    def _pick(self, norm: bytes, forced: Optional[int] = None) -> int:
        """Routing: crc32-hash affinity over ALL configured ranks (so
        a replica's cache keeps its keyspace across restarts), falling
        back to the least-loaded healthy live replica when the
        preferred one is dead or degraded."""
        if forced is not None:
            with self._lock:
                if self._replicas[forced].state != "live":
                    raise FrontError(f"replica {forced} not live")
            return forced
        preferred = 1 + (zlib.crc32(norm) % self._n)
        with self._lock:
            rep = self._replicas[preferred]
            if rep.state == "live" and rep.health in ("ok", "unknown"):
                return preferred
            live = [r for r, rp in self._replicas.items()
                    if rp.state == "live"]
            if not live:
                raise FrontError("no live replicas")
            healthy = [r for r in live
                       if self._replicas[r].health
                       in ("ok", "unknown")] or live
            pick = min(healthy,
                       key=lambda r: self._replicas[r].inflight)
        self._m_fallbacks.inc()
        return pick

    def _submit_to(self, rank: int, req: dict,
                   pend: Optional[_Pending] = None,
                   retryable: bool = True,
                   count_routed: bool = False) -> _Pending:
        if pend is None:
            pend = _Pending(req, retryable)
        wire_id = next(self._ids)
        with self._lock:
            rep = self._replicas[rank]
            if rep.state != "live":
                raise FrontError(f"replica {rank} not live")
            pend.rank = rank
            pend.boot = rep.boot
            self._pending[wire_id] = pend
            rep.inflight += 1
            if count_routed:
                rep.routed += 1
        line = json.dumps({**req, "id": wire_id})
        try:
            with rep.wlock:
                rep.proc.stdin.write(line + "\n")
                rep.proc.stdin.flush()
        except (OSError, ValueError):
            with self._lock:
                self._pending.pop(wire_id, None)
                if rep.inflight > 0:
                    rep.inflight -= 1
            raise FrontError(f"replica {rank} unreachable")
        if count_routed:
            self._m_routed.inc()
        return pend

    def _await(self, pend: _Pending,
               timeout_s: Optional[float] = None) -> dict:
        timeout = timeout_s or self._serve_cfg.replica_timeout_s
        if not pend.event.wait(timeout):
            with self._lock:
                for i, p in list(self._pending.items()):
                    if p is pend:
                        self._pending.pop(i, None)
                        break
            return {"error": f"replica {pend.rank} timed out after "
                             f"{timeout:.0f}s"}
        resp = dict(pend.response or {"error": "no response"})
        return resp

    def _request_op(self, rank: int, req: dict,
                    timeout_s: Optional[float] = None,
                    retryable: bool = True) -> dict:
        pend = self._submit_to(rank, req, retryable=retryable)
        resp = self._await(pend, timeout_s)
        if "error" in resp and "timed out" in str(resp.get("error")):
            raise FrontError(resp["error"])
        return resp

    def handle_request(self, req: dict,
                       rank: Optional[int] = None,
                       timeout_s: Optional[float] = None) -> dict:
        """Route one QUERY request (the op-less protocol shape) to a
        replica and block for its response. ``rank`` forces the route
        (the bench's per-replica warm lever)."""
        from tfidf_tpu import obs
        if not self._admission.wait(
                timeout=self._serve_cfg.replica_timeout_s):
            return {"error": "overloaded"}   # a wedged swap gate
        # Fleet trace context (round 23): minted HERE, at the tier's
        # admission point, and propagated as the request's "trace"
        # JSONL field — the replica adopts it onto its
        # RequestContext, so every span its rid machinery stamps
        # joins back to this route span across the process boundary.
        # The route span covers pick -> submit -> response: after
        # clock alignment it must CONTAIN the replica's request span
        # (the containment tools/trace_check.py --merged pins).
        tctx = disttrace.mint()
        tkw = {"trace": tctx.trace} if tctx is not None else {}
        h = obs.begin("route", **tkw)
        try:
            target = self._pick(self._norm_for(req), forced=rank)
        except FrontError as e:
            obs.end(h, outcome="error")
            return {"error": str(e)}
        if tctx is not None:
            req = {**req, "trace": disttrace.to_wire(tctx)}
        try:
            pend = self._submit_to(target, req, count_routed=True)
        except FrontError:
            # The pick raced a death; one least-loaded retry.
            try:
                target = self._pick(self._norm_for(req))
                pend = self._submit_to(target, req, count_routed=True)
            except FrontError as e:
                obs.end(h, outcome="error")
                return {"error": str(e)}
        resp = self._await(pend, timeout_s)
        # The replica's rid rides the route span's end args: the
        # cross-process join (trace id <-> rid) is recorded on BOTH
        # sides of the hop, so doctor --request can walk it from
        # either end.
        obs.end(h, replica=target, rid=resp.get("rid"))
        return resp

    def query(self, queries, k: Optional[int] = None,
              use_cache: bool = True, rank: Optional[int] = None,
              deadline_ms: Optional[float] = None,
              timeout_s: Optional[float] = None) -> dict:
        """Blocking convenience wrapper (the bench's client)."""
        req: dict = {"queries": list(queries), "k": k or self._k}
        if not use_cache:
            req["use_cache"] = False
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        return self.handle_request(req, rank=rank, timeout_s=timeout_s)

    # --- health + supervision ---------------------------------------

    def _health_loop(self) -> None:
        period = (self._serve_cfg.health_period_ms or 500.0) / 1e3
        while not self._closed:
            time.sleep(period)
            if self._closed:
                return
            for rank in self._live_ranks():
                try:
                    resp = self._request_op(rank, {"op": "healthz"},
                                            timeout_s=10.0,
                                            retryable=False)
                    status = (resp.get("healthz") or {}).get(
                        "status", "unknown")
                except FrontError:
                    status = "unknown"
                with self._lock:
                    rep = self._replicas[rank]
                    if rep.state == "live":
                        rep.health = status

    def _supervise(self) -> None:
        while True:
            rank = self._restart_q.get()
            if rank is None:
                return
            if self._closed:
                continue
            with self._swap_lock:
                if not self._closed:
                    self._restart(rank)

    def _restart(self, rank: int) -> None:
        """Respawn a dead replica from the shared snapshot under the
        restart budget; when the snapshot's epoch disagrees with the
        tier's (a death raced a commit), refresh the snapshot from a
        live peer and boot once more until they agree."""
        from tfidf_tpu.obs import log as obs_log
        rep = self._replicas[rank]
        budget = self._serve_cfg.restart_budget
        while True:
            with self._lock:
                if rep.state != "dead":
                    return
                if rep.restarts >= budget:
                    rep.state = "failed"
                    exhausted = True
                else:
                    rep.restarts += 1
                    exhausted = False
            if exhausted:
                obs_log.log_event(
                    "error", "replica_down",
                    msg=f"replica {rank} restart budget exhausted "
                        f"({budget}); serving without it",
                    replica=rank, boot=rep.boot,
                    reason="budget_exhausted", routed=rep.routed,
                    restarts=budget)
                return
            proc = rep.proc
            if proc is not None:
                try:
                    proc.wait(timeout=10)
                except (subprocess.TimeoutExpired, OSError):
                    self._kill(rank)
            self._spawn(rank, bootstrap=False)
            try:
                self._await_ready(rank)
            except FrontError:
                with self._lock:
                    if rep.state != "down":
                        rep.state = "dead"
                continue
            self._m_restarts.inc()
            # A respawned replica is a NEW clock epoch: re-estimate
            # its offset before any of its spans can be merged.
            self._sync_clock(rank)
            with self._lock:
                behind = rep.epoch != self._epoch
            if not behind:
                return
            # Epoch catch-up: re-snapshot from a live peer, then
            # bounce this replica once more off the fresh snapshot.
            peers = [r for r in self._live_ranks() if r != rank]
            if not peers:
                return    # nothing to catch up FROM; serve as-is
            try:
                self._ctrl_rpc(peers[0], {"op": "snapshot"})
            except FrontError:
                self._kill(peers[0])
            with self._lock:
                rep.state = "stopping"
            self._kill(rank)
            try:
                rep.proc.wait(timeout=10)
            except (subprocess.TimeoutExpired, OSError):
                pass
            with self._lock:
                rep.state = "dead"

    # --- control plane: the two-phase epoch protocol -----------------

    def _ctrl_rpc(self, rank: int, obj: dict,
                  timeout_s: Optional[float] = None) -> dict:
        timeout = timeout_s or self._serve_cfg.replica_timeout_s
        try:
            self._comm.send(rank, _CTRL, json.dumps(obj).encode())
            if not self._comm.poll(rank, timeout):
                raise FrontError(
                    f"replica {rank} ctrl timeout on "
                    f"{obj.get('op')!r} after {timeout:.0f}s")
            return json.loads(self._comm.recv(rank, _CTRL_ACK).decode())
        except (MpiLiteError, OSError, ValueError) as e:
            raise FrontError(
                f"replica {rank} ctrl channel failed on "
                f"{obj.get('op')!r}: {e}")

    def _two_phase(self, kind: str, payload: dict) -> dict:
        """prepare -> ping -> (gate) commit writer-first -> (ungate).
        Raises :class:`SwapAborted` when the transaction dies with the
        tier still on the old epoch, :class:`FrontError` when every
        replica deterministically refused the operation."""
        from tfidf_tpu import obs
        from tfidf_tpu.obs import log as obs_log
        with self._swap_lock:
            if self._closed:
                raise FrontError("front is closed")
            txn = next(self._txns)
            target = self._epoch + 1
            # Control-plane trace context: one id for the whole
            # transaction — every prepare/ping/commit/abort ctrl op
            # carries it and every participant's txn_phase span stamps
            # it, so a tier-wide swap merges into ONE visible tree.
            tctx = disttrace.mint()
            tkw = {"trace": tctx.trace} if tctx is not None else {}
            h = obs.begin("epoch_swap", kind=kind, txn=txn,
                          epoch=target, **tkw)
            try:
                result = self._two_phase_locked(
                    kind, payload, txn, target, obs_log, tctx)
            except SwapAborted:
                obs.end(h, epoch=self._epoch)
                raise
            obs.end(h, epoch=self._epoch)
            return result

    def _two_phase_locked(self, kind: str, payload: dict, txn: int,
                          target: int, obs_log,
                          tctx=None) -> dict:
        from tfidf_tpu import obs
        live = self._live_ranks()
        if not live:
            raise FrontError("no live replicas")
        tkw = {"trace": tctx.trace} if tctx is not None else {}

        def abort_txn(prepared, skip, why_rank, why):
            for peer in prepared:
                if peer == why_rank:
                    continue
                try:
                    self._ctrl_rpc(peer, {"op": "abort", "txn": txn,
                                          **tkw})
                except FrontError:
                    self._kill(peer)
            self._m_aborts.inc()
            obs_log.log_event(
                "warning", "epoch_abort",
                msg=f"epoch {target} ({kind}) aborted — replica "
                    f"{why_rank}: {why}; tier stays on epoch "
                    f"{self._epoch}",
                epoch=target, txn=txn, kind=kind, replica=why_rank,
                reason=str(why)[:200])

        prepared: List[int] = []
        for rank in live:
            try:
                ack = self._ctrl_rpc(rank, {
                    "op": "prepare", "txn": txn, "kind": kind,
                    "epoch": target, **tkw, **payload})
            except FrontError as e:
                abort_txn(prepared, rank, rank, e)
                self._kill(rank)
                raise SwapAborted(f"epoch {target} ({kind}) aborted: "
                                  f"replica {rank}: {e}")
            if not ack.get("ok"):
                err = ack.get("error", "prepare refused")
                abort_txn(prepared + [rank], None, rank, err)
                raise FrontError(f"{kind} refused at prepare by "
                                 f"replica {rank}: {err}")
            prepared.append(rank)
        obs_log.log_event(
            "info", "epoch_prepare",
            msg=f"epoch {target} ({kind}) prepared on "
                f"{len(prepared)} replica(s) (txn {txn})",
            epoch=target, txn=txn, kind=kind, replicas=len(prepared))

        # Ping round: a replica that acked prepare and then died (the
        # SIGKILL-between-phases pin) is caught HERE — nothing has
        # installed yet, so the abort leaves the tier on the old
        # epoch everywhere.
        for rank in prepared:
            try:
                ack = self._ctrl_rpc(rank, {"op": "ping", "txn": txn,
                                            **tkw})
                if not ack.get("ok"):
                    raise FrontError(ack.get("error", "ping refused"))
            except FrontError as e:
                abort_txn(prepared, rank, rank, e)
                self._kill(rank)
                raise SwapAborted(f"epoch {target} ({kind}) aborted: "
                                  f"replica {rank} died between "
                                  f"prepare and commit: {e}")

        # Commit: gate admission so no query is admitted while
        # replicas disagree, writer first so the shared snapshot
        # carries the NEW epoch before anyone else flips.
        self._admission.clear()
        # Drain before anyone flips: a query admitted before the gate
        # closed but still sitting in a replica's queue would be
        # served against the NEW index if that replica committed
        # first — a client-visible mixed-epoch response. Nothing has
        # installed yet, so a drain that stalls aborts back to the
        # old epoch everywhere.
        drain_deadline = (time.monotonic()
                          + self._serve_cfg.replica_timeout_s)
        # The drain-to-zero gap as a first-class span: the txn tree's
        # measurable "where did the swap wait" segment — gate closed,
        # nothing installed, in-flight count bleeding to zero.
        dh = obs.begin("txn_phase", phase="drain", txn=txn,
                       epoch=target, **tkw)
        while True:
            with self._lock:
                inflight = sum(self._replicas[r].inflight
                               for r in prepared
                               if r in self._replicas)
            if inflight == 0:
                obs.end(dh, outcome="drained")
                break
            if time.monotonic() > drain_deadline:
                obs.end(dh, outcome="stalled", inflight=inflight)
                self._admission.set()
                abort_txn(prepared, None, None,
                          FrontError("in-flight drain stalled"))
                raise SwapAborted(
                    f"epoch {target} ({kind}) aborted: {inflight} "
                    f"request(s) still in flight after "
                    f"{self._serve_cfg.replica_timeout_s:.0f}s drain")
            time.sleep(0.002)
        committed: List[tuple] = []
        refused: Optional[str] = None
        try:
            writer = prepared[0]
            for rank in prepared:
                try:
                    ack = self._ctrl_rpc(rank, {
                        "op": "commit", "txn": txn,
                        "snapshot": rank == writer, **tkw})
                except FrontError as e:
                    if rank == writer and not committed:
                        # Writer state unknown; survivors are still
                        # uncommitted — abort them, tier stays old,
                        # the writer's restart heals off a re-made
                        # snapshot (epoch catch-up in _restart).
                        abort_txn([p for p in prepared
                                   if p != writer], None, rank, e)
                        self._kill(rank)
                        raise SwapAborted(
                            f"epoch {target} ({kind}) aborted: "
                            f"writer {rank} died mid-commit: {e}")
                    # Non-writer death after the writer committed:
                    # push forward — the snapshot already carries the
                    # new epoch and the restart catches it up.
                    self._kill(rank)
                    continue
                if not ack.get("ok"):
                    refused = ack.get("error", "commit failed")
                    continue
                committed.append((rank, ack))
            if committed:
                # The front's epoch advances BEFORE the admission
                # gate reopens: no query can be admitted, served on
                # the new index, and returned while the front still
                # reports the old epoch.
                new_epoch = int(committed[0][1].get("epoch", target))
                self._epoch = new_epoch
                with self._lock:
                    for rank, ack in committed:
                        self._replicas[rank].epoch = int(
                            ack.get("epoch", new_epoch))
        finally:
            self._admission.set()

        if not committed:
            # Deterministic refusal — identical state, identical op,
            # identical verdict on every replica; no epoch moved.
            raise FrontError(f"{kind} failed on every replica: "
                             f"{refused}")
        if refused is not None:
            obs_log.log_event(
                "error", "epoch_commit",
                msg=f"PARTIAL commit of epoch {target}: "
                    f"{len(committed)}/{len(prepared)} applied, "
                    f"last refusal: {refused}",
                epoch=target, txn=txn, kind=kind,
                replicas=len(committed), partial=1)
        self._m_commits.inc()
        obs_log.log_event(
            "info", "epoch_commit",
            msg=f"epoch {new_epoch} ({kind}) committed on "
                f"{len(committed)} replica(s) (txn {txn})",
            epoch=new_epoch, txn=txn, kind=kind,
            replicas=len(committed))
        writer_ack = committed[0][1]
        return {**{k: v for k, v in writer_ack.items()
                   if k not in ("ok", "rank", "txn")},
                "epoch": new_epoch, "replicas": len(committed)}

    def swap_index(self, input_dir: str) -> int:
        """Tier-wide hot swap: every replica builds the incoming index
        from ``input_dir`` at prepare, installs at commit. Returns the
        new epoch."""
        return int(self._two_phase("swap",
                                   {"input": input_dir})["epoch"])

    def add_docs(self, docs: List[dict]) -> dict:
        return self._two_phase("add", {"docs": docs})

    def delete_docs(self, names: List[str]) -> dict:
        return self._two_phase("delete", {"names": names})

    def compact_now(self) -> dict:
        return self._two_phase("compact", {})

    def snapshot(self) -> dict:
        """Explicit snapshot from the designated writer (lowest live
        rank) — the restart path's freshness lever."""
        with self._swap_lock:
            live = self._live_ranks()
            if not live:
                raise FrontError("no live replicas")
            ack = self._ctrl_rpc(live[0], {"op": "snapshot"})
            if not ack.get("ok"):
                raise FrontError(f"snapshot failed: "
                                 f"{ack.get('error')}")
            return {"snapshot": self._serve_cfg.snapshot_dir,
                    "epoch": int(ack.get("epoch", self._epoch))}

    # --- merged observability ---------------------------------------

    def _collect_bundles(self, timeout_s: float = 30.0) -> Dict[str,
                                                                dict]:
        bundles: Dict[str, dict] = {}
        for rank in self._live_ranks():
            try:
                resp = self._request_op(rank, {"op": "obs_export"},
                                        timeout_s=timeout_s)
            except FrontError:
                continue
            b = resp.get("obs_export")
            if (isinstance(b, dict) and b.get("schema") == _OBS_SCHEMA
                    and isinstance(b.get("registry"), dict)):
                bundles[f"r{rank}"] = b
        return bundles

    def _merge(self, bundles: Dict[str, dict]):
        from tfidf_tpu.obs.registry import MetricsRegistry
        per = {label: MetricsRegistry.import_state(b["registry"])
               for label, b in bundles.items()}
        merged = MetricsRegistry()
        for reg in per.values():
            merged.merge(reg)
        # The front's own counters ride the fleet view too.
        merged.merge(self._registry)
        return merged, per

    def metrics_snapshot(self) -> dict:
        """The MERGED metrics view: counters summed, histograms merged
        bucket-wise across replicas (obs_agg semantics, in-process),
        with the per-replica snapshots and the front's routing state
        alongside."""
        bundles = self._collect_bundles()
        merged, per = self._merge(bundles)
        return {
            "merged": merged.snapshot(),
            "per_replica": {
                label: {"pid": b.get("pid"), "epoch": b.get("epoch"),
                        "uptime_s": b.get("uptime_s"),
                        "registry": per[label].snapshot()}
                for label, b in sorted(bundles.items())},
            "front": self.describe(),
        }

    def metrics_prom(self) -> str:
        """Merged Prometheus exposition + per-replica
        ``{process="rN"}`` labeled samples (the obs_agg render, served
        straight off the front)."""
        bundles = self._collect_bundles()
        merged, per = self._merge(bundles)

        def esc(v: str) -> str:
            return str(v).replace("\\", "\\\\").replace('"', '\\"')

        lines = [f"# front: {len(per)} replica(s) merged",
                 f"serve_front_processes {len(per)}"]
        lines.append(merged.render_prom().rstrip("\n"))
        for label, reg in sorted(per.items()):
            bundle = bundles[label]
            plabel = f'process="{esc(label)}"'
            lines.append(f"# process {label}: "
                         f"pid={bundle.get('pid')} "
                         f"epoch={bundle.get('epoch')} "
                         f"uptime_s={bundle.get('uptime_s')}")
            snap = reg.snapshot()
            for name, value in sorted(snap.items()):
                if isinstance(value, (int, float)):
                    lines.append(f"{name}{{{plabel}}} {value}")
                elif isinstance(value, dict) and "value" in value:
                    lines.append(f"{name}{{{plabel}}} "
                                 f"{value['value']}")
                elif isinstance(value, dict) and "count" in value:
                    lines.append(f"{name}_count{{{plabel}}} "
                                 f"{value['count']}")
        return "\n".join(lines) + "\n"

    def obs_export(self) -> dict:
        """The tier's federation bundle: merged registry state plus
        per-replica identity — same schema as a single server's, so
        ``tools/obs_agg.py`` can merge fronts of fronts."""
        from tfidf_tpu.obs import log as obs_log
        bundles = self._collect_bundles()
        merged, _ = self._merge(bundles)
        log = obs_log.get_log()
        return {
            "schema": _OBS_SCHEMA,
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "epoch": self._epoch,
            "fingerprint": {"front": True, "n_replicas": self._n,
                            "snapshot_dir":
                                self._serve_cfg.snapshot_dir},
            "registry": merged.export_state(),
            "flight_tail": log.events()[-64:],
            "digest_tail": log.digests()[-32:],
            "replicas": {
                label: {"pid": b.get("pid"), "epoch": b.get("epoch"),
                        "uptime_s": b.get("uptime_s")}
                for label, b in sorted(bundles.items())},
        }

    def trace_export(self) -> dict:
        """The fleet's span evidence in one pull (schema
        ``tfidf-trace/1``): the front's own ring plus every live
        replica's in-memory ring (pulled over the data plane — the
        same transport discipline as ``obs_export``), one entry per
        process carrying the identity + clock-offset metadata
        ``tools/trace_merge.py`` aligns lanes with. Offsets ride the
        METADATA; the Chrome events are each process's verbatim local
        timeline."""
        from tfidf_tpu import obs
        processes: List[dict] = []
        t = obs.get_tracer()
        if t is not None:
            processes.append({**t.export_meta(),
                              "traceEvents": t.chrome_events()})
        for rank in self._live_ranks():
            try:
                resp = self._request_op(rank, {"op": "trace_export"},
                                        timeout_s=30.0)
            except FrontError:
                continue
            b = resp.get("trace_export")
            if not (isinstance(b, dict)
                    and b.get("schema") == _TRACE_SCHEMA):
                continue
            for entry in b.get("processes") or []:
                if not (isinstance(entry, dict)
                        and isinstance(entry.get("traceEvents"),
                                       list)):
                    continue
                entry = dict(entry)
                entry["process"] = f"r{rank}"
                # The front owns the estimator: offset_ns is REPLICA
                # minus FRONT clock, stamped here so every non-front
                # entry of the bundle is alignable.
                entry["clock"] = self._clocks[rank].as_meta()
                processes.append(entry)
        return {"schema": _TRACE_SCHEMA, "pid": os.getpid(),
                "epoch": self._epoch, "processes": processes}

    def replica_info(self) -> Dict[str, dict]:
        """Per-replica identity + compile receipts (the bench's
        recompiles-after-warm audit)."""
        out: Dict[str, dict] = {}
        for rank in self._live_ranks():
            try:
                resp = self._request_op(rank, {"op": "replica_info"},
                                        timeout_s=30.0)
            except FrontError:
                continue
            info = resp.get("replica_info")
            if isinstance(info, dict):
                out[f"r{rank}"] = info
        return out

    # --- the JSONL protocol ------------------------------------------

    def handle_line(self, line: str, write: Callable[[dict], None]
                    ) -> bool:
        """One JSONL request -> one JSON response line; the front's
        counterpart of ``cli._serve_handle_line``. Returns False on
        shutdown."""
        line = line.strip()
        if not line:
            return True
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as e:
            write({"error": f"bad request: {e}"})
            return True
        rid = req.get("id")
        op = req.get("op")
        if op == "shutdown":
            return False
        try:
            if op is None:
                queries = req.get("queries")
                if not isinstance(queries, list) or not all(
                        isinstance(q, str) for q in queries):
                    write({"id": rid, "error": "bad request: "
                           "'queries' must be a list of strings"})
                    return True
                resp = self.handle_request(
                    {k: v for k, v in req.items() if k != "id"})
                resp["id"] = rid
                write(resp)
            elif op == "metrics":
                write({"id": rid, "metrics": self.metrics_snapshot()})
            elif op == "metrics_prom":
                write({"id": rid, "metrics_prom": self.metrics_prom()})
            elif op == "obs_export":
                write({"id": rid, "obs_export": self.obs_export()})
            elif op == "trace_export":
                write({"id": rid, "trace_export": self.trace_export()})
            elif op in ("healthz", "readyz"):
                desc = self.describe()
                if op == "readyz":
                    write({"id": rid, "readyz": {
                        "ready": desc["live"] > 0,
                        "live": desc["live"],
                        "n_replicas": self._n}})
                else:
                    write({"id": rid, "healthz": desc})
            elif op == "replica_info":
                write({"id": rid, "replica_info": self.replica_info()})
            elif op == "swap_index":
                epoch = self.swap_index(req["input"])
                write({"id": rid, "swapped": True, "epoch": epoch})
            elif op == "add_docs":
                docs = req.get("docs")
                if (not isinstance(docs, list) or not docs
                        or not all(isinstance(d, dict)
                                   and isinstance(d.get("name"), str)
                                   and isinstance(d.get("text"), str)
                                   for d in docs)):
                    write({"id": rid, "error": "bad request: 'docs' "
                           "must be a non-empty list of "
                           "{\"name\": str, \"text\": str}"})
                    return True
                out = self.add_docs(docs)
                write({"id": rid, **out})
            elif op == "delete_docs":
                names = req.get("names")
                if (not isinstance(names, list) or not names
                        or not all(isinstance(n, str)
                                   for n in names)):
                    write({"id": rid, "error": "bad request: 'names' "
                           "must be a non-empty list of strings"})
                    return True
                out = self.delete_docs(names)
                write({"id": rid, **out})
            elif op == "compact":
                write({"id": rid, **self.compact_now()})
            elif op == "snapshot":
                write({"id": rid, **self.snapshot()})
            else:
                write({"id": rid, "error": f"unknown op {op!r}"})
        except SwapAborted as e:
            write({"id": rid, "error": f"swap aborted: {e}",
                   "epoch": self._epoch})
        except (FrontError, KeyError, ValueError, OSError) as e:
            write({"id": rid, "error": str(e)})
        return True


# --- the replica worker ----------------------------------------------


def _replica_main(spec_path: str) -> int:
    """One replica process: attach to the front's mpi_lite channel,
    restore (or bootstrap-build) the index from the shared snapshot,
    serve the stdin/stdout JSONL data plane with the SAME handler as
    ``tfidf serve``, and answer the two-phase control plane on a
    daemon thread. stdout carries ONLY protocol JSONL — the ready
    line is the first of it."""
    with open(spec_path) as f:
        spec = json.load(f)
    comm = MpiLiteComm.from_env()
    rank, boot = comm.rank, int(spec.get("boot", 0))

    from tfidf_tpu import checkpoint as ckpt
    from tfidf_tpu import faults
    from tfidf_tpu.cli import _serve_handle_line
    from tfidf_tpu.config import ServeConfig, apply_compile_cache
    from tfidf_tpu.models import TfidfRetriever
    from tfidf_tpu.models.retrieval import _search_bcoo
    from tfidf_tpu.parallel.multihost import _config_from_spec

    from tfidf_tpu.serve.server import TfidfServer

    cfg = _config_from_spec(spec["pipeline"])
    apply_compile_cache(cfg.compile_cache)
    serve_cfg = ServeConfig(**spec["serve"])
    if serve_cfg.disttrace is not None:
        disttrace.configure(serve_cfg.disttrace)
    if spec.get("disttrace"):
        # The replica inherits no TFIDF_TPU_TRACE (_STRIP_ENV): the
        # front's spec flag arms an IN-MEMORY span ring instead,
        # pulled on demand over the data plane by the trace_export
        # op. Identity rides the export metadata; the front stamps
        # the clock offset when it collects the bundle.
        from tfidf_tpu import obs
        if obs.get_tracer() is None:
            obs.set_tracer(obs.Tracer(), None)
        obs.set_export_meta(process=f"r{rank}")
    strict = not spec.get("no_strict", False)
    snap_dir = spec["snapshot_dir"]
    bootstrap = bool(spec.get("bootstrap"))
    k = int(spec.get("k", 10))

    def build_retriever(input_dir: str) -> TfidfRetriever:
        return TfidfRetriever(cfg).index_dir(
            input_dir, strict=strict, doc_len=spec.get("doc_len"))

    def fail(msg: str) -> int:
        sys.stderr.write(f"replica {rank}: {msg}\n")
        return 3

    retriever = None
    meta = None
    segments = None
    if serve_cfg.delta_docs:
        from tfidf_tpu.index import SegmentedIndex
        if ckpt.exists(snap_dir):
            try:
                segments, meta = SegmentedIndex.restore(snap_dir, cfg)
            except ckpt.SnapshotMismatch as e:
                if not bootstrap:
                    return fail(f"snapshot at {snap_dir} unusable "
                                f"({e})")
        if segments is None:
            if not bootstrap or not spec.get("input_dir"):
                return fail(f"no usable snapshot at {snap_dir}")
            segments = SegmentedIndex.from_dir(
                spec["input_dir"], cfg,
                delta_docs=serve_cfg.delta_docs,
                compact_at=serve_cfg.compact_at, strict=strict)
        retriever = segments.view()
    else:
        if ckpt.exists(snap_dir):
            try:
                retriever, meta = TfidfRetriever.restore(snap_dir, cfg)
            except ckpt.SnapshotMismatch as e:
                if not bootstrap:
                    return fail(f"snapshot at {snap_dir} unusable "
                                f"({e})")
        if retriever is None:
            if not bootstrap or not spec.get("input_dir"):
                return fail(f"no usable snapshot at {snap_dir}")
            retriever = build_retriever(spec["input_dir"])

    server = TfidfServer(
        retriever, serve_cfg,
        initial_epoch=int(meta.get("epoch", 0)) if meta else 0)
    if segments is not None:
        server.attach_segments(segments)
    if bootstrap and meta is None:
        # First boot on an empty snapshot root: persist so ranks 2..N
        # (and every restart) spin up without touching the corpus.
        server.snapshot(snap_dir)

    # pow2 warm on the installed index, then draw the warm line —
    # everything after this is a steady-state recompile.
    _, installed = server.current_index()
    b = 1
    while b <= serve_cfg.max_batch:
        installed.search([""] * b, k=k)
        b *= 2
    server.mark_warm()

    wlock = threading.Lock()

    def write(obj) -> None:
        with wlock:
            sys.stdout.write(json.dumps(obj) + "\n")
            sys.stdout.flush()

    staged: Dict[int, tuple] = {}

    def apply_commit(kind: str, prepared) -> dict:
        if kind == "swap":
            return {"epoch": server.swap_index(prepared)}
        if kind == "add":
            out = server.add_docs(prepared["names"],
                                  prepared["texts"])
            return {"epoch": out["epoch"], "added": out["added"],
                    "updated": out["updated"],
                    "sealed": out["sealed"]}
        if kind == "delete":
            out = server.delete_docs(prepared["names"])
            return {"epoch": out["epoch"], "deleted": out["deleted"],
                    "missing": out["missing"]}
        if kind == "compact":
            server.compact_now(force=True)
            return {"epoch": server.epoch}
        raise ValueError(f"unknown commit kind {kind!r}")

    def ctrl_loop() -> None:
        from tfidf_tpu import obs
        while True:
            try:
                req = json.loads(comm.recv(0, _CTRL).decode())
            except (MpiLiteError, OSError, ValueError):
                os._exit(0)     # front gone — nothing left to serve
            op = req.get("op")
            txn = req.get("txn")
            # Participant half of the txn tree (round 23): each
            # two-phase op this replica executes is a txn_phase span
            # stamped with the transaction's fleet trace id, so a
            # tier-wide swap merges into one tree across processes.
            tid_wire = req.get("trace")
            ph = (obs.begin("txn_phase", phase=op, txn=txn,
                            **({"trace": tid_wire}
                               if isinstance(tid_wire, str) else {}))
                  if op in ("prepare", "ping", "commit", "abort")
                  else None)
            ack: dict = {"ok": True, "rank": rank, "txn": txn}
            fire_text = None
            try:
                if op == "prepare":
                    kind = req["kind"]
                    target = int(req["epoch"])
                    if kind == "swap":
                        staged[txn] = ("swap",
                                       build_retriever(req["input"]))
                    elif kind == "add":
                        names = [d["name"] for d in req["docs"]]
                        texts = [d["text"] for d in req["docs"]]
                        if not names:
                            raise ValueError("add: no docs")
                        staged[txn] = ("add", {"names": names,
                                               "texts": texts})
                    elif kind == "delete":
                        names = list(req["names"])
                        if not names:
                            raise ValueError("delete: no names")
                        staged[txn] = ("delete", {"names": names})
                    elif kind == "compact":
                        staged[txn] = ("compact", None)
                    else:
                        raise ValueError(
                            f"unknown prepare kind {kind!r}")
                    ack["epoch"] = server.epoch
                    fire_text = (f"replica={rank} boot={boot} "
                                 f"epoch={target}")
                elif op == "ping":
                    ack["epoch"] = server.epoch
                elif op == "commit":
                    kind, prepared = staged.pop(txn)
                    ack.update(apply_commit(kind, prepared))
                    if req.get("snapshot"):
                        server.snapshot(snap_dir)
                elif op == "abort":
                    staged.pop(txn, None)
                    ack["epoch"] = server.epoch
                elif op == "snapshot":
                    server.snapshot(snap_dir)
                    ack["epoch"] = server.epoch
                elif op == "clock_sync":
                    # The offset handshake's replica half: one local
                    # clock reading while holding the request — the
                    # front brackets it with its own send/recv stamps
                    # (RTT-midpoint estimate, obs/disttrace.py).
                    ack["t_ns"] = time.perf_counter_ns()
                else:
                    raise ValueError(f"unknown ctrl op {op!r}")
            except Exception as e:  # noqa: BLE001 — acked, not fatal
                ack = {"ok": False, "rank": rank, "txn": txn,
                       "error": str(e)}
            if ph is not None:
                obs.end(ph, ok=bool(ack.get("ok")),
                        epoch=ack.get("epoch"))
            try:
                comm.send(0, _CTRL_ACK, json.dumps(ack).encode())
            except (MpiLiteError, OSError):
                os._exit(0)
            if fire_text is not None and ack.get("ok"):
                try:
                    faults.fire("replica_prepare", text=fire_text,
                                replica=rank, boot=boot)
                except faults.InjectedFault:
                    # The chaos rehearsal's SIGKILL stand-in: die
                    # between prepare-ack and commit, no cleanup —
                    # the front's ping round must catch this.
                    os._exit(137)

    threading.Thread(target=ctrl_loop, daemon=True,
                     name=f"replica{rank}-ctrl").start()

    write({"ready": True, "rank": rank, "boot": boot,
           "epoch": server.epoch, "num_docs": server.num_docs,
           "pid": os.getpid()})
    try:
        for line in sys.stdin:
            sline = line.strip()
            if not sline:
                continue
            try:
                req = json.loads(sline)
            except ValueError as e:
                write({"error": f"bad request: {e}"})
                continue
            if (isinstance(req, dict)
                    and req.get("op") == "replica_info"):
                write({"id": req.get("id"), "replica_info": {
                    "rank": rank, "boot": boot, "pid": os.getpid(),
                    "epoch": server.epoch,
                    "num_docs": server.num_docs,
                    "compiled_programs": _search_bcoo._cache_size(),
                    "recompiles_after_warm":
                        server.compile_watch.recompile_count}})
                continue
            if not _serve_handle_line(server, sline, write, k,
                                      build_retriever, None):
                break
    finally:
        server.close(drain=True)
        comm.close()
    return 0


if __name__ == "__main__":
    sys.exit(_replica_main(sys.argv[1]))
