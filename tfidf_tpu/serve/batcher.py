"""Dynamic micro-batching: a thread-safe submit queue in front of the
batch search kernel.

The retrieval kernel is batch-shaped (one [V, Q] block per dispatch)
but online traffic arrives as many small concurrent requests. The
bridge is Clipper-style deadline-bounded coalescing: ``submit``
enqueues a request and returns a ``concurrent.futures.Future``; a
single worker thread drains the queue into device batches under the
policy

* flush when the coalesced batch reaches ``max_batch`` queries, or
* when the OLDEST queued request has waited ``max_wait_ms`` —

so a full system never waits and an idle system adds at most one wait
window of latency. Batches group by ``(k, group)`` (the server passes
its ``(epoch, retriever)`` snapshot as ``group``, so one batch never
mixes indexes across a hot swap, and ``k`` is static in the compiled
program). Query counts are power-of-two bucketed inside
``TfidfRetriever.search`` itself, so steady-state serving re-uses a
handful of compiled programs per k (the compile-count pin in
tests/test_serve.py).

Requests stay atomic: one request's queries always score in one batch
(a request larger than ``max_batch`` overflows its batch alone —
``search`` blocks internally), and per-query results are independent,
so slicing a coalesced batch back per request is exact.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Deque, List, Optional, Sequence, Tuple, Union

from collections import deque

import numpy as np

from tfidf_tpu import obs
from tfidf_tpu.obs import devmon as obs_devmon


class ServeError(RuntimeError):
    """Base class for typed serving-layer failures."""


class Overloaded(ServeError):
    """Admission control shed the request: the in-flight query backlog
    is at ``queue_depth``. Clients should back off and retry."""


class DeadlineExceeded(ServeError):
    """The request's deadline expired while it was still queued; it was
    shed without touching the device."""


def _pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


class _Pending:
    __slots__ = ("queries", "k", "group", "future", "deadline",
                 "enqueued_at", "obs")

    def __init__(self, queries, k, group, deadline):
        self.queries = queries
        self.k = k
        self.group = group
        self.future: Future = Future()
        self.deadline = deadline          # absolute monotonic, or None
        self.enqueued_at = time.monotonic()
        # Queue-wait span: opens at submit, closes when the batch forms
        # (batch-id attributed) or the request sheds — the "queued"
        # stage of the request lifecycle chain (docs/OBSERVABILITY.md).
        self.obs = obs.begin("queued", queries=len(self.queries),
                             k=self.k)


class MicroBatcher:
    """Coalesces concurrent submits into padded device batches.

    Args:
      search_fn: ``(queries, k, group) -> (vals, ids)`` — the batch
        kernel (the server binds this to the epoch-snapshotted
        retriever's ``search``).
      max_batch: flush threshold in queries.
      max_wait_ms: oldest-request wait bound before a partial flush.
      metrics: optional :class:`~tfidf_tpu.serve.metrics.ServeMetrics`
        for batch-occupancy and deadline-shed counters.
      heartbeat: optional zero-arg liveness callback the worker thread
        invokes every loop wake and around every batch — the
        :class:`~tfidf_tpu.obs.health.HealthMonitor` stall signal (a
        busy batcher that stops beating is a wedged pipeline).
    """

    def __init__(self, search_fn: Callable, *, max_batch: int = 64,
                 max_wait_ms: float = 2.0, metrics=None,
                 heartbeat: Optional[Callable[[], None]] = None,
                 thread_name: str = "tfidf-serve-batcher") -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        self._search_fn = search_fn
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1e3
        self._metrics = metrics
        self._heartbeat = heartbeat
        self._batch_seq = 0   # trace batch-id; worker thread only
        self._queue: Deque[_Pending] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain_on_close = True
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=thread_name)
        self._worker.start()

    # --- submit side ---
    def submit(self, queries: Sequence[Union[str, bytes]], k: int,
               group=None, deadline: Optional[float] = None) -> Future:
        """Enqueue one request; the Future resolves to the ``(vals,
        ids)`` pair for exactly these queries (rows in submit order).
        ``deadline`` is an absolute ``time.monotonic()`` instant; a
        request still queued past it fails with
        :class:`DeadlineExceeded`."""
        p = _Pending(list(queries), int(k), group, deadline)
        with self._cond:
            if self._closed:
                raise ServeError("batcher is closed")
            self._queue.append(p)
            self._cond.notify_all()
        return p.future

    def queued_queries(self) -> int:
        with self._cond:
            return sum(len(p.queries) for p in self._queue)

    # --- worker side ---
    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until a batch is due under the deadline policy, then
        pop it. Returns None only at close time with an empty queue."""
        with self._cond:
            while True:
                if self._heartbeat is not None:
                    self._heartbeat()
                if not self._queue:
                    if self._closed:
                        return None
                    self._cond.wait()
                    continue
                head = self._queue[0]
                now = time.monotonic()
                flush_at = head.enqueued_at + self.max_wait
                if (self._ready_queries(head) >= self.max_batch
                        or now >= flush_at or self._closed):
                    return self._pop_batch(head)
                self._cond.wait(timeout=flush_at - now)

    def _ready_queries(self, head: _Pending) -> int:
        return sum(len(p.queries) for p in self._queue
                   if p.k == head.k and p.group == head.group)

    def _pop_batch(self, head: _Pending) -> List[_Pending]:
        """Pop the head plus every queued request with the same (k,
        group) until ``max_batch`` queries — FIFO within the key;
        other keys keep their queue positions."""
        batch: List[_Pending] = []
        taken = 0
        remaining: Deque[_Pending] = deque()
        for p in self._queue:
            compatible = p.k == head.k and p.group == head.group
            if (compatible
                    and (taken + len(p.queries) <= self.max_batch
                         or not batch)):
                batch.append(p)
                taken += len(p.queries)
            else:
                remaining.append(p)
        self._queue = remaining
        return batch

    def _run(self) -> None:
        while True:
            if self._heartbeat is not None:
                self._heartbeat()
            batch = self._take_batch()
            if batch is None:
                return
            self._execute(batch)
            if self._heartbeat is not None:
                self._heartbeat()

    def _execute(self, batch: List[_Pending]) -> None:
        obs.name_thread("batcher")
        now = time.monotonic()
        live: List[_Pending] = []
        for p in batch:
            if self._closed and not self._drain_on_close:
                obs.end(p.obs, outcome="rejected")
                p.future.set_exception(ServeError("server closed"))
            elif p.deadline is not None and now >= p.deadline:
                if self._metrics is not None:
                    self._metrics.count("shed_deadline")
                obs.end(p.obs, outcome="shed_deadline")
                p.future.set_exception(DeadlineExceeded(
                    f"deadline expired {now - p.deadline:.3f}s before "
                    f"the batch formed"))
            else:
                live.append(p)
        if not live:
            return
        bid = self._batch_seq
        self._batch_seq += 1
        queries: List = []
        offsets = [0]
        for p in live:
            obs.end(p.obs, outcome="batched", batch=bid)
            queries.extend(p.queries)
            offsets.append(len(queries))
        # Recompile attribution (round 12): with a warm CompileWatch
        # armed, a recompile-count delta across THIS batch's device
        # call pins the offending batch on the trace timeline — the
        # flight event (obs/devmon.py) says which program, the
        # instant says when in the serve loop it struck.
        watch = obs_devmon.get_watch()
        pre_rc = (watch.recompile_count
                  if watch is not None and watch.warm else None)
        with obs.span("batched", batch=bid, queries=len(queries),
                      requests=len(live)):
            try:
                # TraceAnnotation-wrapped: the device lanes of a
                # profiler capture carry the same batch id.
                with obs.device_span("device", batch=bid,
                                     queries=len(queries)):
                    vals, ids = self._search_fn(queries, live[0].k,
                                                live[0].group)
            except BaseException as e:  # noqa: BLE001 — deliver
                for p in live:
                    p.future.set_exception(e)
                return
            if (pre_rc is not None
                    and watch.recompile_count > pre_rc):
                obs.instant("recompile_in_batch", batch=bid,
                            queries=len(queries))
            if self._metrics is not None:
                self._metrics.observe_batch(len(queries),
                                            _pow2(len(queries)))
            vals, ids = np.asarray(vals), np.asarray(ids)
            for p, lo, hi in zip(live, offsets, offsets[1:]):
                p.future.set_result((vals[lo:hi], ids[lo:hi]))

    # --- shutdown ---
    def close(self, drain: bool = True) -> None:
        """Stop accepting work and join the worker. ``drain=True``
        serves everything already queued first; ``drain=False`` fails
        queued requests with :class:`ServeError`."""
        with self._cond:
            if self._closed:
                self._cond.notify_all()
            self._closed = True
            self._drain_on_close = drain
            self._cond.notify_all()
        self._worker.join()

    @property
    def closed(self) -> bool:
        return self._closed
