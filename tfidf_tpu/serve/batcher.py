"""Dynamic micro-batching: a thread-safe submit queue in front of the
batch search kernel.

The retrieval kernel is batch-shaped (one [V, Q] block per dispatch)
but online traffic arrives as many small concurrent requests. The
bridge is Clipper-style deadline-bounded coalescing: ``submit``
enqueues a request and returns a ``concurrent.futures.Future``; a
single worker thread drains the queue into device batches under the
policy

* flush when the coalesced batch reaches ``max_batch`` queries, or
* when the OLDEST queued request has waited ``max_wait_ms`` —

so a full system never waits and an idle system adds at most one wait
window of latency. Batches group by ``(k, group)`` (the server passes
its ``(epoch, retriever)`` snapshot as ``group``, so one batch never
mixes indexes across a hot swap, and ``k`` is static in the compiled
program). Query counts are power-of-two bucketed inside
``TfidfRetriever.search`` itself, so steady-state serving re-uses a
handful of compiled programs per k (the compile-count pin in
tests/test_serve.py).

Requests stay atomic: one request's queries always score in one batch
(a request larger than ``max_batch`` overflows its batch alone —
``search`` blocks internally), and per-query results are independent,
so slicing a coalesced batch back per request is exact.

Survival (round 13): the worker thread is SUPERVISED — an exception
escaping the loop (a bug, or an injected ``batcher_loop`` fault)
restarts it with backoff inside a restart budget instead of leaving a
zombie server whose health page can only narrate the wedge; past the
budget the batcher declares itself dead, fails everything queued, and
``submit`` raises. With a
:class:`~tfidf_tpu.serve.supervisor.SupervisedDispatch` attached, the
device call itself gets bounded retry and poison-query bisection: a
batch that fails persistently is split until the poison queries are
isolated (their requests fail with the typed :class:`PoisonQuery`),
and every innocent co-batched request still resolves bit-identically.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Deque, List, Optional, Sequence, Union

from collections import deque

import numpy as np

from tfidf_tpu import faults, obs
from tfidf_tpu.obs import devmon as obs_devmon
from tfidf_tpu.obs import log as obs_log


class ServeError(RuntimeError):
    """Base class for typed serving-layer failures."""


class Overloaded(ServeError):
    """Admission control shed the request: the in-flight query backlog
    is at ``queue_depth``. Clients should back off and retry."""


class DeadlineExceeded(ServeError):
    """The request's deadline expired while it was still queued; it was
    shed without touching the device."""


class ServerClosed(ServeError):
    """The server (or batcher) is closed: the operation raced a
    shutdown and was refused, not lost — retry against a live
    replica. ``swap_index``/``submit`` raise this instead of
    deadlocking against a draining close."""


class PoisonQuery(ServeError):
    """The request contained a query isolated as poison (its dispatch
    fails deterministically) or already quarantined. The rest of its
    batch was unaffected; resubmitting the same query fails fast
    (the 4xx of this protocol)."""

    def __init__(self, msg: str, queries: Sequence = ()):
        super().__init__(msg)
        self.queries = list(queries)


def _pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


class _Pending:
    __slots__ = ("queries", "k", "group", "future", "deadline",
                 "enqueued_at", "obs", "ctx")

    def __init__(self, queries, k, group, deadline, ctx=None):
        self.queries = queries
        self.k = k
        self.group = group
        self.future: Future = Future()
        self.deadline = deadline          # absolute monotonic, or None
        self.enqueued_at = time.monotonic()
        # Request forensics (round 16): the server's RequestContext
        # rides the pending entry so the batcher can stamp its rid on
        # the queued span and mark the queue/batch/device phases the
        # slow-query breakdown reports.
        self.ctx = ctx
        # Queue-wait span: opens at submit, closes when the batch forms
        # (batch-id attributed) or the request sheds — the "queued"
        # stage of the request lifecycle chain (docs/OBSERVABILITY.md).
        if ctx is not None:
            self.obs = obs.begin("queued", queries=len(self.queries),
                                 k=self.k, rid=ctx.rid)
        else:
            self.obs = obs.begin("queued", queries=len(self.queries),
                                 k=self.k)


class MicroBatcher:
    """Coalesces concurrent submits into padded device batches.

    Args:
      search_fn: ``(queries, k, group) -> (vals, ids)`` — the batch
        kernel (the server binds this to the epoch-snapshotted
        retriever's ``search``).
      max_batch: flush threshold in queries.
      max_wait_ms: oldest-request wait bound before a partial flush.
      metrics: optional :class:`~tfidf_tpu.serve.metrics.ServeMetrics`
        for batch-occupancy and deadline-shed counters.
      heartbeat: optional zero-arg liveness callback the worker thread
        invokes every loop wake and around every batch — the
        :class:`~tfidf_tpu.obs.health.HealthMonitor` stall signal (a
        busy batcher that stops beating is a wedged pipeline).
      supervisor: optional :class:`~tfidf_tpu.serve.supervisor.
        SupervisedDispatch` — the device call then gets bounded retry
        and poison bisection; None keeps the bare round-9 dispatch
        (one failure fails the whole batch).
      restart_budget: worker-loop crash restarts tolerated before the
        batcher declares itself dead (fails queued work, refuses
        submits). 0 disables supervision (a loop crash is fatal
        immediately).
      restart_backoff_ms: base of the jittered exponential backoff
        between loop restarts.
    """

    def __init__(self, search_fn: Callable, *, max_batch: int = 64,
                 max_wait_ms: float = 2.0, metrics=None,
                 heartbeat: Optional[Callable[[], None]] = None,
                 supervisor=None, restart_budget: int = 3,
                 restart_backoff_ms: float = 50.0,
                 thread_name: str = "tfidf-serve-batcher") -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if restart_budget < 0:
            raise ValueError("restart_budget must be >= 0")
        self._search_fn = search_fn
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1e3
        self._metrics = metrics
        self._heartbeat = heartbeat
        self._supervisor = supervisor
        self._restart_budget = restart_budget
        self._restart_backoff_ms = restart_backoff_ms
        self.restarts = 0
        self._dead = False
        self._batch_seq = 0   # trace batch-id; worker thread only
        self._queue: Deque[_Pending] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain_on_close = True
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=thread_name)
        self._worker.start()

    # --- submit side ---
    def submit(self, queries: Sequence[Union[str, bytes]], k: int,
               group=None, deadline: Optional[float] = None,
               ctx=None) -> Future:
        """Enqueue one request; the Future resolves to the ``(vals,
        ids)`` pair for exactly these queries (rows in submit order).
        ``deadline`` is an absolute ``time.monotonic()`` instant; a
        request still queued past it fails with
        :class:`DeadlineExceeded`. ``ctx`` is the server's optional
        :class:`~tfidf_tpu.obs.reqtrace.RequestContext` — the request
        identity stamped through the span chain."""
        p = _Pending(list(queries), int(k), group, deadline, ctx=ctx)
        with self._cond:
            if self._closed:
                raise ServerClosed("batcher is closed")
            if self._dead:
                raise ServeError(
                    f"batcher worker is dead (restart budget "
                    f"{self._restart_budget} exhausted)")
            self._queue.append(p)
            self._cond.notify_all()
        return p.future

    def queued_queries(self) -> int:
        with self._cond:
            return sum(len(p.queries) for p in self._queue)

    # --- worker side ---
    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until a batch is due under the deadline policy, then
        pop it. Returns None only at close time with an empty queue."""
        with self._cond:
            while True:
                if self._heartbeat is not None:
                    self._heartbeat()
                if not self._queue:
                    if self._closed:
                        return None
                    self._cond.wait()
                    continue
                head = self._queue[0]
                now = time.monotonic()
                flush_at = head.enqueued_at + self.max_wait
                if (self._ready_queries(head) >= self.max_batch
                        or now >= flush_at or self._closed):
                    return self._pop_batch(head)
                self._cond.wait(timeout=flush_at - now)

    def _ready_queries(self, head: _Pending) -> int:
        return sum(len(p.queries) for p in self._queue
                   if p.k == head.k and p.group == head.group)

    def _pop_batch(self, head: _Pending) -> List[_Pending]:
        """Pop the head plus every queued request with the same (k,
        group) until ``max_batch`` queries — FIFO within the key;
        other keys keep their queue positions."""
        batch: List[_Pending] = []
        taken = 0
        remaining: Deque[_Pending] = deque()
        for p in self._queue:
            compatible = p.k == head.k and p.group == head.group
            if (compatible
                    and (taken + len(p.queries) <= self.max_batch
                         or not batch)):
                batch.append(p)
                taken += len(p.queries)
            else:
                remaining.append(p)
        self._queue = remaining
        return batch

    def _run(self) -> None:
        """Supervision wrapper: restart the loop on a crash (with
        backoff, inside the restart budget) so an exception escaping
        the batching machinery — a bug, or an injected
        ``batcher_loop`` fault — never leaves a zombie server whose
        queue silently grows forever. Queued requests survive a
        restart untouched (the deque is shared state, not loop
        state); past the budget everything queued fails with a typed
        error and the batcher refuses new work."""
        while True:
            try:
                self._loop()
                return                  # clean exit: close() observed
            except BaseException as e:  # noqa: BLE001 — supervised
                self.restarts += 1
                if self._metrics is not None:
                    self._metrics.count("worker_restarts")
                over = self.restarts > self._restart_budget
                obs_log.log_event(
                    "error" if over else "warning",
                    "worker_restart",
                    msg=f"batcher loop crashed "
                        f"({type(e).__name__}: {e}); "
                        + ("restart budget exhausted — batcher is "
                           "dead" if over else
                           f"restart {self.restarts}/"
                           f"{self._restart_budget}"),
                    worker="batcher", restart=self.restarts,
                    error=type(e).__name__)
                obs.instant("worker_restart", worker="batcher",
                            restart=self.restarts)
                if over or self._closed:
                    self._die(e)
                    return
                time.sleep(faults.backoff_s(
                    self.restarts, self._restart_backoff_ms))

    def _die(self, err: BaseException) -> None:
        with self._cond:
            self._dead = True
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for p in pending:
            obs.end(p.obs, outcome="error")
            p.future.set_exception(ServeError(
                f"batcher worker died: {type(err).__name__}: {err}"))

    def _loop(self) -> None:
        while True:
            if self._heartbeat is not None:
                self._heartbeat()
            faults.fire("batcher_loop")
            batch = self._take_batch()
            if batch is None:
                return
            self._execute(batch)
            if self._heartbeat is not None:
                self._heartbeat()

    def _execute(self, batch: List[_Pending]) -> None:
        obs.name_thread("batcher")
        now = time.monotonic()
        live: List[_Pending] = []
        for p in batch:
            if self._closed and not self._drain_on_close:
                obs.end(p.obs, outcome="rejected")
                p.future.set_exception(ServeError("server closed"))
            elif p.deadline is not None and now >= p.deadline:
                if self._metrics is not None:
                    self._metrics.count("shed_deadline")
                obs.end(p.obs, outcome="shed_deadline")
                p.future.set_exception(DeadlineExceeded(
                    f"deadline expired {now - p.deadline:.3f}s before "
                    f"the batch formed"))
            else:
                live.append(p)
        if not live:
            return
        bid = self._batch_seq
        self._batch_seq += 1
        t_formed = time.monotonic()
        queries: List = []
        offsets = [0]
        for p in live:
            obs.end(p.obs, outcome="batched", batch=bid)
            if p.ctx is not None:
                # queue_wait measured at the same instant the queued
                # span ends — the breakdown and the trace record one
                # interval (the 5%+5ms reconciliation pin).
                p.ctx.mark("queue_wait", t_formed - p.enqueued_at)
            queries.extend(p.queries)
            offsets.append(len(queries))
        rids = [p.ctx.rid for p in live if p.ctx is not None]
        span_extra = {"rids": rids} if rids else {}
        for p in live:
            if p.ctx is not None:
                p.ctx.batch = bid
                p.ctx.co_occupants = len(queries)
        # Recompile attribution (round 12): with a warm CompileWatch
        # armed, a recompile-count delta across THIS batch's device
        # call pins the offending batch on the trace timeline — the
        # flight event (obs/devmon.py) says which program, the
        # instant says when in the serve loop it struck.
        watch = obs_devmon.get_watch()
        pre_rc = (watch.recompile_count
                  if watch is not None and watch.warm else None)
        # Retry attribution (round 16): the counter delta across this
        # batch's supervised dispatch charges dispatch_retry backoffs
        # to the requests that rode the batch — a slow_query event
        # then SAYS its tail came from retries, not queueing.
        pre_retries = self._retry_count()
        with obs.span("batched", batch=bid, queries=len(queries),
                      requests=len(live), **span_extra):
            poison: List[int] = []
            try:
                # TraceAnnotation-wrapped: the device lanes of a
                # profiler capture carry the same batch id.
                t_dev0 = time.monotonic()
                with obs.device_span("device", batch=bid,
                                     queries=len(queries),
                                     **span_extra):
                    if self._supervisor is not None:
                        vals, ids, poison = self._supervisor.run_batch(
                            queries, live[0].k, live[0].group,
                            batch_id=bid, rids=rids or None)
                    else:
                        faults.fire("device_dispatch",
                                    queries=len(queries), batch=bid)
                        vals, ids = self._search_fn(queries, live[0].k,
                                                    live[0].group)
                t_dev1 = time.monotonic()
                for p in live:
                    if p.ctx is not None:
                        p.ctx.mark("batch_wait", t_dev0 - t_formed)
                        p.ctx.mark("device", t_dev1 - t_dev0)
                        p.ctx.mark_device_end(t_dev1)
            except BaseException as e:  # noqa: BLE001 — deliver
                for p in live:
                    p.future.set_exception(e)
                return
            retry_delta = self._retry_count() - pre_retries
            if retry_delta:
                for p in live:
                    if p.ctx is not None:
                        p.ctx.note("dispatch_retry", n=retry_delta)
            if (pre_rc is not None
                    and watch.recompile_count > pre_rc):
                obs.instant("recompile_in_batch", batch=bid,
                            queries=len(queries))
                for p in live:
                    if p.ctx is not None:
                        p.ctx.note("recompile_in_batch")
            if self._metrics is not None:
                self._metrics.observe_batch(len(queries),
                                            _pow2(len(queries)))
            if not poison:
                vals, ids = np.asarray(vals), np.asarray(ids)
                for p, lo, hi in zip(live, offsets, offsets[1:]):
                    p.future.set_result((vals[lo:hi], ids[lo:hi]))
                return
            # Poison isolation: requests carrying a poison query fail
            # with the typed error (naming THEIR poison queries);
            # every innocent request resolves from the bisection's
            # per-query rows — bit-identical to a clean dispatch.
            pset = set(poison)
            for p, lo, hi in zip(live, offsets, offsets[1:]):
                bad = [j - lo for j in range(lo, hi) if j in pset]
                if bad:
                    p.future.set_exception(PoisonQuery(
                        f"{len(bad)} of {hi - lo} queries in this "
                        f"request poisoned batch {bid} and were "
                        f"quarantined",
                        queries=[p.queries[b] for b in bad]))
                else:
                    p.future.set_result((vals[lo:hi], ids[lo:hi]))

    def _retry_count(self):
        """Current ``serve_dispatch_retries_total`` (0 without metrics
        or before the first retry created the counter)."""
        if self._metrics is None:
            return 0
        inst = self._metrics.registry.get("serve_dispatch_retries_total")
        return inst.value if inst is not None else 0

    # --- shutdown ---
    def close(self, drain: bool = True) -> None:
        """Stop accepting work and join the worker. ``drain=True``
        serves everything already queued first; ``drain=False`` fails
        queued requests with :class:`ServeError`."""
        with self._cond:
            if self._closed:
                self._cond.notify_all()
            self._closed = True
            self._drain_on_close = drain
            self._cond.notify_all()
        self._worker.join()

    @property
    def closed(self) -> bool:
        return self._closed
