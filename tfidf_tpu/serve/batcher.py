"""Dynamic micro-batching: a thread-safe submit queue in front of the
batch search kernel.

The retrieval kernel is batch-shaped (one [V, Q] block per dispatch)
but online traffic arrives as many small concurrent requests. The
bridge is Clipper-style deadline-bounded coalescing: ``submit``
enqueues a request and returns a ``concurrent.futures.Future``; a
single worker thread drains the queue into device batches under the
policy

* flush when the coalesced batch reaches ``max_batch`` queries, or
* when the OLDEST queued request has waited ``max_wait_ms`` —

so a full system never waits and an idle system adds at most one wait
window of latency. Batches group by ``(k, group)`` (the server passes
its ``(epoch, retriever)`` snapshot as ``group``, so one batch never
mixes indexes across a hot swap, and ``k`` is static in the compiled
program). Query counts are power-of-two bucketed inside
``TfidfRetriever.search`` itself, so steady-state serving re-uses a
handful of compiled programs per k (the compile-count pin in
tests/test_serve.py).

Requests stay atomic: one request's queries always score in one batch
(a request larger than ``max_batch`` overflows its batch alone —
``search`` blocks internally), and per-query results are independent,
so slicing a coalesced batch back per request is exact.

Survival (round 13): the worker thread is SUPERVISED — an exception
escaping the loop (a bug, or an injected ``batcher_loop`` fault)
restarts it with backoff inside a restart budget instead of leaving a
zombie server whose health page can only narrate the wedge; past the
budget the batcher declares itself dead, fails everything queued, and
``submit`` raises. With a
:class:`~tfidf_tpu.serve.supervisor.SupervisedDispatch` attached, the
device call itself gets bounded retry and poison-query bisection: a
batch that fails persistently is split until the poison queries are
isolated (their requests fail with the typed :class:`PoisonQuery`),
and every innocent co-batched request still resolves bit-identically.

Pipelined execution (round 22): with ``pipeline_depth >= 2`` batch
execution splits into two stages so the device never idles between
dispatches. The batcher thread becomes a pure DISPATCH stage — it
fills the slab slot, issues the (already-async) jitted search plus
the D2H copy of the result words through ``dispatch_fn`` (a
``(queries, k, group) -> PendingSearch``), and immediately returns to
coalescing the next batch. A single ordered DRAIN worker (the ingest
``_DrainAhead`` discipline: one worker = batch-major resolution)
materializes results FIFO, releases slab slots, and resolves futures.
The in-flight window is bounded at ``pipeline_depth`` batches; the
dispatch stage blocks (heartbeating) when it is full. Failures
surface at the drain stage — jax defers device errors to the first
host read — so the supervisor's retry/breaker/bisection machinery
runs AT DRAIN TIME (``SupervisedDispatch.run_batch``'s ``first``
seam), re-dispatching through the same ordered window: batches
dispatched after a failing one drain after its recovery completes,
never reordered. Responses are bit-identical to direct search at
every depth (the dispatch stage and the synchronous path share one
implementation — ``TfidfRetriever.search_async``), and a batch
admitted at epoch E resolves against E: the ``group`` snapshot rides
the in-flight entry. ``pipeline_depth=1`` keeps the legacy one-stage
``_execute`` path, byte for byte.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Deque, List, Optional, Sequence, Union

from collections import deque

import numpy as np

from tfidf_tpu import faults, obs
from tfidf_tpu.obs import devmon as obs_devmon
from tfidf_tpu.obs import log as obs_log


class ServeError(RuntimeError):
    """Base class for typed serving-layer failures."""


class Overloaded(ServeError):
    """Admission control shed the request: the in-flight query backlog
    is at ``queue_depth``. Clients should back off and retry."""


class DeadlineExceeded(ServeError):
    """The request's deadline expired while it was still queued; it was
    shed without touching the device."""


class ServerClosed(ServeError):
    """The server (or batcher) is closed: the operation raced a
    shutdown and was refused, not lost — retry against a live
    replica. ``swap_index``/``submit`` raise this instead of
    deadlocking against a draining close."""


class PoisonQuery(ServeError):
    """The request contained a query isolated as poison (its dispatch
    fails deterministically) or already quarantined. The rest of its
    batch was unaffected; resubmitting the same query fails fast
    (the 4xx of this protocol)."""

    def __init__(self, msg: str, queries: Sequence = ()):
        super().__init__(msg)
        self.queries = list(queries)


def _pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


class _Resolved:
    """Already-materialized stand-in for a ``PendingSearch`` — wraps a
    synchronous ``search_fn`` result so the pipelined machinery has
    one drain path whether or not the dispatch could defer."""

    __slots__ = ("_r",)

    def __init__(self, result):
        self._r = result

    def materialize(self):
        return self._r


class _InFlight:
    """One dispatched-but-undrained batch in the pipeline window."""

    __slots__ = ("bid", "live", "queries", "offsets", "rids", "pending",
                 "error", "t_formed", "t_dev0", "span", "dev")

    def __init__(self, bid, live, queries, offsets, rids):
        self.bid = bid
        self.live = live            # _Pending entries riding the batch
        self.queries = queries
        self.offsets = offsets
        self.rids = rids
        self.pending = None         # PendingSearch-shaped handle
        self.error = None           # dispatch-stage failure, deferred
        self.t_formed = 0.0
        self.t_dev0 = 0.0
        self.span = None            # open "batched" span handle
        self.dev = None             # open "device" span handle


class _Pending:
    __slots__ = ("queries", "k", "group", "future", "deadline",
                 "enqueued_at", "obs", "ctx")

    def __init__(self, queries, k, group, deadline, ctx=None):
        self.queries = queries
        self.k = k
        self.group = group
        self.future: Future = Future()
        self.deadline = deadline          # absolute monotonic, or None
        self.enqueued_at = time.monotonic()
        # Request forensics (round 16): the server's RequestContext
        # rides the pending entry so the batcher can stamp its rid on
        # the queued span and mark the queue/batch/device phases the
        # slow-query breakdown reports.
        self.ctx = ctx
        # Queue-wait span: opens at submit, closes when the batch forms
        # (batch-id attributed) or the request sheds — the "queued"
        # stage of the request lifecycle chain (docs/OBSERVABILITY.md).
        if ctx is not None:
            kw = {"queries": len(self.queries), "k": self.k,
                  "rid": ctx.rid}
            if getattr(ctx, "trace", None):
                kw["trace"] = ctx.trace   # fleet trace id (round 23)
            self.obs = obs.begin("queued", **kw)
        else:
            self.obs = obs.begin("queued", queries=len(self.queries),
                                 k=self.k)


class MicroBatcher:
    """Coalesces concurrent submits into padded device batches.

    Args:
      search_fn: ``(queries, k, group) -> (vals, ids)`` — the batch
        kernel (the server binds this to the epoch-snapshotted
        retriever's ``search``).
      max_batch: flush threshold in queries.
      max_wait_ms: oldest-request wait bound before a partial flush.
      metrics: optional :class:`~tfidf_tpu.serve.metrics.ServeMetrics`
        for batch-occupancy and deadline-shed counters.
      heartbeat: optional zero-arg liveness callback the worker thread
        invokes every loop wake and around every batch — the
        :class:`~tfidf_tpu.obs.health.HealthMonitor` stall signal (a
        busy batcher that stops beating is a wedged pipeline).
      supervisor: optional :class:`~tfidf_tpu.serve.supervisor.
        SupervisedDispatch` — the device call then gets bounded retry
        and poison bisection; None keeps the bare round-9 dispatch
        (one failure fails the whole batch).
      restart_budget: worker-loop crash restarts tolerated before the
        batcher declares itself dead (fails queued work, refuses
        submits). 0 disables supervision (a loop crash is fatal
        immediately).
      restart_backoff_ms: base of the jittered exponential backoff
        between loop restarts.
      pipeline_depth: bounded in-flight window (round 22) — up to
        this many dispatched batches overlap with coalescing and
        with each other's drains. 1 (the default here; the server
        config defaults to 2) keeps the legacy single-stage path.
      dispatch_fn: ``(queries, k, group) -> PendingSearch`` — the
        async dispatch stage (the server binds
        ``TfidfRetriever.search_async``). Only consulted at
        ``pipeline_depth >= 2``; absent, the pipeline still runs its
        staged machinery over the synchronous ``search_fn`` (no
        device overlap, same ordering/recovery semantics — the
        duck-typed fallback for retrievers without a dispatch stage).
    """

    def __init__(self, search_fn: Callable, *, max_batch: int = 64,
                 max_wait_ms: float = 2.0, metrics=None,
                 heartbeat: Optional[Callable[[], None]] = None,
                 supervisor=None, restart_budget: int = 3,
                 restart_backoff_ms: float = 50.0,
                 pipeline_depth: int = 1,
                 dispatch_fn: Optional[Callable] = None,
                 thread_name: str = "tfidf-serve-batcher") -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if restart_budget < 0:
            raise ValueError("restart_budget must be >= 0")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        self._search_fn = search_fn
        self._dispatch_fn = dispatch_fn
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1e3
        self.pipeline_depth = pipeline_depth
        self._metrics = metrics
        self._heartbeat = heartbeat
        self._supervisor = supervisor
        self._restart_budget = restart_budget
        self._restart_backoff_ms = restart_backoff_ms
        self.restarts = 0
        self._dead = False
        self._batch_seq = 0   # trace batch-id; worker thread only
        self._queue: Deque[_Pending] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain_on_close = True
        # Pipelined window state, all under _icond: the in-flight ring
        # the dispatch stage appends to and the drain worker pops
        # FIFO. A separate condition from _cond so a full window never
        # contends with the submit path.
        self._icond = threading.Condition()
        self._inflight: Deque[_InFlight] = deque()
        self._drain_stop = False
        self._pipe_streak = False   # batcher thread only: bubble det.
        self._inflight_gauge = None
        self._drainer: Optional[threading.Thread] = None
        if pipeline_depth > 1:
            if metrics is not None:
                self._inflight_gauge = metrics.registry.gauge(
                    "serve_inflight_batches",
                    "dispatched batches not yet drained (the "
                    "pipelined execution window)")
            self._drainer = threading.Thread(
                target=self._drain_run, daemon=True,
                name=thread_name + "-drain")
            self._drainer.start()
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=thread_name)
        self._worker.start()

    # --- submit side ---
    def submit(self, queries: Sequence[Union[str, bytes]], k: int,
               group=None, deadline: Optional[float] = None,
               ctx=None) -> Future:
        """Enqueue one request; the Future resolves to the ``(vals,
        ids)`` pair for exactly these queries (rows in submit order).
        ``deadline`` is an absolute ``time.monotonic()`` instant; a
        request still queued past it fails with
        :class:`DeadlineExceeded`. ``ctx`` is the server's optional
        :class:`~tfidf_tpu.obs.reqtrace.RequestContext` — the request
        identity stamped through the span chain."""
        p = _Pending(list(queries), int(k), group, deadline, ctx=ctx)
        with self._cond:
            if self._closed:
                raise ServerClosed("batcher is closed")
            if self._dead:
                raise ServeError(
                    f"batcher worker is dead (restart budget "
                    f"{self._restart_budget} exhausted)")
            self._queue.append(p)
            self._cond.notify_all()
        return p.future

    def queued_queries(self) -> int:
        with self._cond:
            return sum(len(p.queries) for p in self._queue)

    def inflight_batches(self) -> int:
        """Dispatched-but-undrained batches in the pipeline window
        (always 0 at depth 1 — execution is single-stage there)."""
        with self._icond:
            return len(self._inflight)

    # --- worker side ---
    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until a batch is due under the deadline policy, then
        pop it. Returns None only at close time with an empty queue."""
        with self._cond:
            while True:
                if self._heartbeat is not None:
                    self._heartbeat()
                if not self._queue:
                    if self._closed:
                        return None
                    # Going idle ends a pipelined burst: the next
                    # dispatch onto an empty window is a fresh start,
                    # not a bubble (batcher thread only).
                    self._pipe_streak = False
                    self._cond.wait()
                    continue
                head = self._queue[0]
                now = time.monotonic()
                flush_at = head.enqueued_at + self.max_wait
                if (self._ready_queries(head) >= self.max_batch
                        or now >= flush_at or self._closed):
                    return self._pop_batch(head)
                self._cond.wait(timeout=flush_at - now)

    def _ready_queries(self, head: _Pending) -> int:
        return sum(len(p.queries) for p in self._queue
                   if p.k == head.k and p.group == head.group)

    def _pop_batch(self, head: _Pending) -> List[_Pending]:
        """Pop the head plus every queued request with the same (k,
        group) until ``max_batch`` queries — FIFO within the key;
        other keys keep their queue positions."""
        batch: List[_Pending] = []
        taken = 0
        remaining: Deque[_Pending] = deque()
        for p in self._queue:
            compatible = p.k == head.k and p.group == head.group
            if (compatible
                    and (taken + len(p.queries) <= self.max_batch
                         or not batch)):
                batch.append(p)
                taken += len(p.queries)
            else:
                remaining.append(p)
        self._queue = remaining
        return batch

    def _run(self) -> None:
        """Supervision wrapper: restart the loop on a crash (with
        backoff, inside the restart budget) so an exception escaping
        the batching machinery — a bug, or an injected
        ``batcher_loop`` fault — never leaves a zombie server whose
        queue silently grows forever. Queued requests survive a
        restart untouched (the deque is shared state, not loop
        state); past the budget everything queued fails with a typed
        error and the batcher refuses new work."""
        while True:
            try:
                self._loop()
                return                  # clean exit: close() observed
            except BaseException as e:  # noqa: BLE001 — supervised
                self.restarts += 1
                if self._metrics is not None:
                    self._metrics.count("worker_restarts")
                over = self.restarts > self._restart_budget
                obs_log.log_event(
                    "error" if over else "warning",
                    "worker_restart",
                    msg=f"batcher loop crashed "
                        f"({type(e).__name__}: {e}); "
                        + ("restart budget exhausted — batcher is "
                           "dead" if over else
                           f"restart {self.restarts}/"
                           f"{self._restart_budget}"),
                    worker="batcher", restart=self.restarts,
                    error=type(e).__name__)
                obs.instant("worker_restart", worker="batcher",
                            restart=self.restarts)
                if over or self._closed:
                    self._die(e)
                    return
                time.sleep(faults.backoff_s(
                    self.restarts, self._restart_backoff_ms))

    def _die(self, err: BaseException) -> None:
        with self._cond:
            self._dead = True
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for p in pending:
            obs.end(p.obs, outcome="error")
            p.future.set_exception(ServeError(
                f"batcher worker died: {type(err).__name__}: {err}"))

    def _loop(self) -> None:
        while True:
            if self._heartbeat is not None:
                self._heartbeat()
            faults.fire("batcher_loop")
            batch = self._take_batch()
            if batch is None:
                return
            if self.pipeline_depth > 1:
                self._dispatch(batch)
            else:
                self._execute(batch)
            if self._heartbeat is not None:
                self._heartbeat()

    def _screen(self, batch: List[_Pending]) -> List[_Pending]:
        """Shed entries a formed batch can no longer serve (closing
        without drain, expired deadline); returns the live rest."""
        now = time.monotonic()
        live: List[_Pending] = []
        for p in batch:
            if self._closed and not self._drain_on_close:
                obs.end(p.obs, outcome="rejected")
                p.future.set_exception(ServeError("server closed"))
            elif p.deadline is not None and now >= p.deadline:
                if self._metrics is not None:
                    self._metrics.count("shed_deadline")
                obs.end(p.obs, outcome="shed_deadline")
                p.future.set_exception(DeadlineExceeded(
                    f"deadline expired {now - p.deadline:.3f}s before "
                    f"the batch formed"))
            else:
                live.append(p)
        return live

    def _form(self, live: List[_Pending]):
        """Assign the batch id, close the queued spans, flatten the
        requests: -> (bid, t_formed, queries, offsets, rids)."""
        bid = self._batch_seq
        self._batch_seq += 1
        t_formed = time.monotonic()
        queries: List = []
        offsets = [0]
        for p in live:
            obs.end(p.obs, outcome="batched", batch=bid)
            if p.ctx is not None:
                # queue_wait measured at the same instant the queued
                # span ends — the breakdown and the trace record one
                # interval (the 5%+5ms reconciliation pin).
                p.ctx.mark("queue_wait", t_formed - p.enqueued_at)
            queries.extend(p.queries)
            offsets.append(len(queries))
        rids = [p.ctx.rid for p in live if p.ctx is not None]
        for p in live:
            if p.ctx is not None:
                p.ctx.batch = bid
                p.ctx.co_occupants = len(queries)
        return bid, t_formed, queries, offsets, rids

    @staticmethod
    def _span_extra(live, rids) -> dict:
        """rid + fleet-trace stamps for a batch's spans: ``rids`` is
        positional (round 16); ``traces`` (round 23) is the deduped
        set of front-minted trace ids riding the batch, so a merged
        tier timeline joins batched/device/drain spans to the front's
        route spans without going through the rid table."""
        extra = {"rids": rids} if rids else {}
        traces = sorted({p.ctx.trace for p in live
                         if p.ctx is not None
                         and getattr(p.ctx, "trace", None)})
        if traces:
            extra["traces"] = traces
        return extra

    def _deliver(self, live, offsets, vals, ids, poison, bid) -> None:
        """Slice the batch result back per request and resolve the
        futures (poison rows fail typed, innocents get their rows)."""
        if not poison:
            vals, ids = np.asarray(vals), np.asarray(ids)
            for p, lo, hi in zip(live, offsets, offsets[1:]):
                p.future.set_result((vals[lo:hi], ids[lo:hi]))
            return
        # Poison isolation: requests carrying a poison query fail
        # with the typed error (naming THEIR poison queries);
        # every innocent request resolves from the bisection's
        # per-query rows — bit-identical to a clean dispatch.
        pset = set(poison)
        for p, lo, hi in zip(live, offsets, offsets[1:]):
            bad = [j - lo for j in range(lo, hi) if j in pset]
            if bad:
                p.future.set_exception(PoisonQuery(
                    f"{len(bad)} of {hi - lo} queries in this "
                    f"request poisoned batch {bid} and were "
                    f"quarantined",
                    queries=[p.queries[b] for b in bad]))
            else:
                p.future.set_result((vals[lo:hi], ids[lo:hi]))

    def _execute(self, batch: List[_Pending]) -> None:
        obs.name_thread("batcher")
        live = self._screen(batch)
        if not live:
            return
        bid, t_formed, queries, offsets, rids = self._form(live)
        span_extra = self._span_extra(live, rids)
        # Recompile attribution (round 12): with a warm CompileWatch
        # armed, a recompile-count delta across THIS batch's device
        # call pins the offending batch on the trace timeline — the
        # flight event (obs/devmon.py) says which program, the
        # instant says when in the serve loop it struck.
        watch = obs_devmon.get_watch()
        pre_rc = (watch.recompile_count
                  if watch is not None and watch.warm else None)
        # Retry attribution (round 16): the counter delta across this
        # batch's supervised dispatch charges dispatch_retry backoffs
        # to the requests that rode the batch — a slow_query event
        # then SAYS its tail came from retries, not queueing.
        pre_retries = self._retry_count()
        with obs.span("batched", batch=bid, queries=len(queries),
                      requests=len(live), **span_extra):
            poison: List[int] = []
            try:
                # TraceAnnotation-wrapped: the device lanes of a
                # profiler capture carry the same batch id.
                t_dev0 = time.monotonic()
                with obs.device_span("device", batch=bid,
                                     queries=len(queries),
                                     **span_extra):
                    if self._supervisor is not None:
                        vals, ids, poison = self._supervisor.run_batch(
                            queries, live[0].k, live[0].group,
                            batch_id=bid, rids=rids or None)
                    else:
                        faults.fire("device_dispatch",
                                    queries=len(queries), batch=bid)
                        vals, ids = self._search_fn(queries, live[0].k,
                                                    live[0].group)
                t_dev1 = time.monotonic()
                for p in live:
                    if p.ctx is not None:
                        p.ctx.mark("batch_wait", t_dev0 - t_formed)
                        p.ctx.mark("device", t_dev1 - t_dev0)
                        p.ctx.mark_device_end(t_dev1)
            except BaseException as e:  # noqa: BLE001 — deliver
                for p in live:
                    p.future.set_exception(e)
                return
            retry_delta = self._retry_count() - pre_retries
            if retry_delta:
                for p in live:
                    if p.ctx is not None:
                        p.ctx.note("dispatch_retry", n=retry_delta)
            if (pre_rc is not None
                    and watch.recompile_count > pre_rc):
                obs.instant("recompile_in_batch", batch=bid,
                            queries=len(queries))
                for p in live:
                    if p.ctx is not None:
                        p.ctx.note("recompile_in_batch")
            if self._metrics is not None:
                self._metrics.observe_batch(len(queries),
                                            _pow2(len(queries)))
            self._deliver(live, offsets, vals, ids, poison, bid)

    # --- pipelined path (round 22): dispatch stage + drain worker ---
    def _dispatch(self, batch: List[_Pending]) -> None:
        """Stage 1 of the pipeline (batcher thread): screen, form,
        issue the async device call, park the in-flight entry for the
        drain worker. Blocks only while the window is full — never on
        device results — so the device always has the next batch
        queued behind the one it is crunching."""
        obs.name_thread("batcher")
        live = self._screen(batch)
        if not live:
            return
        # Window admission BEFORE forming: batch ids and queued-span
        # outcomes are assigned in admission order, so the drain
        # worker's FIFO pop is batch-major by construction.
        # The drain worker outlives the dispatch worker (close() joins
        # it second), so this wait always makes progress — and the
        # window never exceeds depth, which is what lets the slab ring
        # pre-provision exactly ``depth`` slots per bucket.
        with self._icond:
            while len(self._inflight) >= self.pipeline_depth:
                if self._heartbeat is not None:
                    self._heartbeat()
                self._icond.wait(0.05)
        was_empty = len(self._inflight) == 0
        bubble = was_empty and self._pipe_streak
        live = self._screen(live)
        if not live:
            return
        bid, t_formed, queries, offsets, rids = self._form(live)
        span_extra = self._span_extra(live, rids)
        watch = obs_devmon.get_watch()
        pre_rc = (watch.recompile_count
                  if watch is not None and watch.warm else None)
        ent = _InFlight(bid, live, queries, offsets, rids)
        ent.t_formed = t_formed
        # The batched + device spans BEGIN here on the batcher lane
        # and END at drain — obs records a span on the thread that
        # began it, so the trace shape (device nested in batched on
        # the batcher lane, rids attached) is identical at any depth.
        ent.span = obs.begin("batched", batch=bid, queries=len(queries),
                             requests=len(live), **span_extra)
        ent.t_dev0 = time.monotonic()
        ent.dev = obs.begin("device", batch=bid, queries=len(queries),
                            **span_extra)
        try:
            # Async issue: the jitted call returns device futures; the
            # synchronous part (tracing/compile) still happens HERE,
            # which keeps recompile attribution on the dispatch side.
            if self._dispatch_fn is not None:
                ent.pending = self._dispatch_fn(queries, live[0].k,
                                                live[0].group)
            else:
                ent.pending = _Resolved(self._search_fn(
                    queries, live[0].k, live[0].group))
        except BaseException as e:  # noqa: BLE001 — fail at drain,
            ent.error = e          # in order, like any device error
        if (pre_rc is not None and watch.recompile_count > pre_rc):
            obs.instant("recompile_in_batch", batch=bid,
                        queries=len(queries))
            for p in live:
                if p.ctx is not None:
                    p.ctx.note("recompile_in_batch")
        with self._icond:
            self._inflight.append(ent)
            if self._inflight_gauge is not None:
                self._inflight_gauge.set(len(self._inflight))
            self._icond.notify_all()
        self._pipe_streak = True
        if bubble:
            # The device went idle between dispatches while work kept
            # arriving — the window drained to zero mid-streak.
            if self._metrics is not None:
                self._metrics.count("pipeline_bubbles")
            obs.instant("serve_pipeline_bubble", batch=bid)

    def _drain_run(self) -> None:
        """Drain worker: materialize in-flight batches strictly in
        dispatch order (one worker == batch-major resolution), release
        their futures, keep the heartbeat alive through long waits."""
        obs.name_thread("drain")
        while True:
            with self._icond:
                # No heartbeat on the IDLE wait: an empty window means
                # the dispatch worker owns liveness (it beats from
                # _take_batch and the window wait), and a wedged loop
                # with queued work must still starve the monitor into
                # the stall signal. The drain worker beats only while
                # it is actually draining — the in-flight waits that
                # used to starve the heartbeat.
                while not self._inflight and not self._drain_stop:
                    self._icond.wait(0.1)
                if not self._inflight and self._drain_stop:
                    return
                ent = self._inflight[0]   # peek; pop after resolution
            try:
                self._resolve(ent)
            except BaseException as e:  # noqa: BLE001 — never die
                for p in ent.live:
                    if not p.future.done():
                        p.future.set_exception(e)
            with self._icond:
                self._inflight.popleft()
                if self._inflight_gauge is not None:
                    self._inflight_gauge.set(len(self._inflight))
                self._icond.notify_all()
            if self._heartbeat is not None:
                self._heartbeat()

    def _resolve(self, ent: _InFlight) -> None:
        """Stage 2 (drain thread): wait for the device, run the
        supervision story (retry / breaker / poison bisection) exactly
        as the unpipelined path would, mark phases, deliver."""
        live, bid, queries = ent.live, ent.bid, ent.queries
        rids, offsets = ent.rids, ent.offsets
        span_extra = self._span_extra(live, rids)
        pre_retries = self._retry_count()
        err: Optional[BaseException] = None
        # The drain span closes BEFORE the batched span ends: the
        # whole resolution nests inside the batch's dispatch-to-
        # deliver lifetime (trace_check pins the containment).
        with obs.span("drain", batch=bid, queries=len(queries),
                      **span_extra):
            poison: List[int] = []
            try:
                # Attempt 1 consumes the already-dispatched pending
                # (or re-raises the captured dispatch error); retries
                # and bisection halves re-dispatch synchronously —
                # the fault seam, attempt accounting and breaker
                # story are the legacy path's, verbatim.
                def first(ent=ent):
                    if ent.error is not None:
                        raise ent.error
                    return ent.pending.materialize()
                if self._supervisor is not None:
                    # The supervisor fires the device_dispatch seam
                    # itself, once per attempt — same budget burn as
                    # the unpipelined path.
                    vals, ids, poison = self._supervisor.run_batch(
                        queries, live[0].k, live[0].group,
                        batch_id=bid, rids=rids or None, first=first)
                else:
                    faults.fire("device_dispatch",
                                queries=len(queries), batch=bid)
                    vals, ids = first()
                t_mat = time.monotonic()
                obs.end(ent.dev)
                ent.dev = None
                for p in live:
                    if p.ctx is not None:
                        p.ctx.mark("batch_wait", ent.t_dev0 - ent.t_formed)
                        p.ctx.mark("device", t_mat - ent.t_dev0)
                        p.ctx.mark_device_end(t_mat)
            except BaseException as e:  # noqa: BLE001 — deliver
                err = e
                if ent.dev is not None:
                    obs.end(ent.dev, outcome="error")
                    ent.dev = None
            else:
                retry_delta = self._retry_count() - pre_retries
                if retry_delta:
                    for p in live:
                        if p.ctx is not None:
                            p.ctx.note("dispatch_retry", n=retry_delta)
                if self._metrics is not None:
                    self._metrics.observe_batch(len(queries),
                                                _pow2(len(queries)))
                self._deliver(live, offsets, vals, ids, poison, bid)
        if err is not None:
            obs.end(ent.span, outcome="error")
            ent.span = None
            for p in live:
                p.future.set_exception(err)
            return
        obs.end(ent.span)
        ent.span = None

    def _retry_count(self):
        """Current ``serve_dispatch_retries_total`` (0 without metrics
        or before the first retry created the counter)."""
        if self._metrics is None:
            return 0
        inst = self._metrics.registry.get("serve_dispatch_retries_total")
        return inst.value if inst is not None else 0

    # --- shutdown ---
    def close(self, drain: bool = True) -> None:
        """Stop accepting work and join the worker. ``drain=True``
        serves everything already queued first; ``drain=False`` fails
        queued requests with :class:`ServeError`."""
        with self._cond:
            if self._closed:
                self._cond.notify_all()
            self._closed = True
            self._drain_on_close = drain
            self._cond.notify_all()
        self._worker.join()
        if self._drainer is not None:
            # Worker joined => everything it will ever dispatch is in
            # the window; tell the drainer to exit once it's empty and
            # wait — close() returns with zero batches in flight.
            with self._icond:
                self._drain_stop = True
                self._icond.notify_all()
            self._drainer.join()

    @property
    def closed(self) -> bool:
        return self._closed
