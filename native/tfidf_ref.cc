// tfidf_ref — clean-room native bit-reference for the TF-IDF pipeline.
//
// Reproduces the *semantics and output bytes* of the reference program
// (SURVEY §2-§3: discover -> bcast -> map TF -> reduce DF -> bcast ->
// score -> gather -> sort -> emit; TFIDF.c:52-287) while fixing its
// hazards (SURVEY §2.5): no 32-record caps, no fixed char buffers, no
// mis-extent wire types, no data races. This is the `--backend=mpi`
// oracle the JAX/TPU path is diffed against.
//
// Parallel structure mirrors the reference exactly:
//   * rank 0 is a pure coordinator: discovers the corpus (TFIDF.c:98-110),
//     receives the DF reduction, gathers, sorts, writes (TFIDF.c:260-283);
//   * worker rank r owns documents r, r+(size-1), r+2(size-1), ...
//     (static round-robin, TFIDF.c:130);
//   * refuses idle workers: size-1 > numDocs is a hard error
//     (TFIDF.c:120-123).
//
// Usage:
//   tfidf_ref <input_dir> <output_file> [nranks]   (thread backend)
//   mpirun -np N tfidf_ref <input_dir> <output_file>   (MPI build)

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <filesystem>
#include <fstream>
#include <string>
#include <unordered_map>
#include <vector>

#include "comm.h"

#ifdef TFIDF_HAVE_MPI
#include <mpi.h>  // main() owns MPI_Init/Finalize in the MPI build
#endif

namespace tfidf {
namespace {

// ----- serialization helpers (length-prefixed, little-endian) -----

void PutU32(std::vector<uint8_t>& b, uint32_t v) {
  b.insert(b.end(), {(uint8_t)v, (uint8_t)(v >> 8), (uint8_t)(v >> 16),
                     (uint8_t)(v >> 24)});
}

uint32_t GetU32(const std::vector<uint8_t>& b, size_t& off) {
  uint32_t v = b[off] | b[off + 1] << 8 | b[off + 2] << 16 |
               (uint32_t)b[off + 3] << 24;
  off += 4;
  return v;
}

void PutStr(std::vector<uint8_t>& b, const std::string& s) {
  PutU32(b, (uint32_t)s.size());
  b.insert(b.end(), s.begin(), s.end());
}

std::string GetStr(const std::vector<uint8_t>& b, size_t& off) {
  uint32_t n = GetU32(b, off);
  std::string s((const char*)b.data() + off, n);
  off += n;
  return s;
}

// ----- DF table: insertion-ordered word -> doc-count map -----
//
// Same shape as the reference's u_w table (TFIDF.c:37-42) minus the
// 32-cap and the in-band length channel (SURVEY §2.5-1,-3): length is
// explicit in the wire format, capacity is dynamic.
struct DfTable {
  std::vector<std::string> words;       // insertion order
  std::vector<int64_t> doc_counts;      // parallel to words
  std::unordered_map<std::string, size_t> index;

  void Add(const std::string& w, int64_t n) {
    auto it = index.find(w);
    if (it == index.end()) {
      index.emplace(w, words.size());
      words.push_back(w);
      doc_counts.push_back(n);
    } else {
      doc_counts[it->second] += n;
    }
  }

  std::vector<uint8_t> Serialize() const {
    std::vector<uint8_t> out;
    PutU32(out, (uint32_t)words.size());
    for (size_t i = 0; i < words.size(); ++i) {
      PutStr(out, words[i]);
      PutU32(out, (uint32_t)doc_counts[i]);
    }
    return out;
  }

  static DfTable Deserialize(const std::vector<uint8_t>& buf) {
    DfTable t;
    size_t off = 0;
    uint32_t n = GetU32(buf, off);
    for (uint32_t i = 0; i < n; ++i) {
      std::string w = GetStr(buf, off);
      uint32_t c = GetU32(buf, off);
      t.Add(w, c);
    }
    return t;
  }
};

// Merge src-rank accumulator into dst — the CustomReduce semantics
// (TFIDF.c:291-319): sum counts for known words, append unknown words in
// src order. Applied in ascending rank order (Comm::ReduceToRoot), which
// reproduces the reference's non-commutative ordered fold (TFIDF.c:324).
void MergeDf(const std::vector<uint8_t>& src, std::vector<uint8_t>& dst) {
  DfTable d = DfTable::Deserialize(dst);
  size_t off = 0;
  uint32_t n = GetU32(src, off);
  for (uint32_t i = 0; i < n; ++i) {
    std::string w = GetStr(src, off);
    uint32_t c = GetU32(src, off);
    d.Add(w, c);
  }
  dst = d.Serialize();
}

// ----- tokenizer: fscanf("%s") semantics (TFIDF.c:142-147) -----
// Fixed ASCII whitespace (the C-locale isspace set) rather than the
// locale-dependent std::isspace, so output is environment-independent.
inline bool IsSpaceByte(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\v' || c == '\f' ||
         c == '\r';
}

std::vector<std::string> Tokenize(const std::string& data) {
  std::vector<std::string> toks;
  size_t i = 0, n = data.size();
  while (i < n) {
    while (i < n && IsSpaceByte((unsigned char)data[i])) ++i;
    size_t start = i;
    while (i < n && !IsSpaceByte((unsigned char)data[i])) ++i;
    if (i > start) toks.emplace_back(data.substr(start, i - start));
  }
  return toks;
}

struct Record {  // the reference's obj struct (TFIDF.c:26-35), dynamic
  std::string doc;
  std::string word;
  int64_t count;
  int64_t doc_size;
};

int PipelineMain(Comm& comm, const std::string& input_dir,
                 const std::string& output_path) {
  const int rank = comm.rank(), size = comm.size();

  // Phase 0: discovery on the coordinator (TFIDF.c:98-110), then
  // broadcast of numDocs (TFIDF.c:115).
  std::vector<uint8_t> meta(8, 0);
  if (rank == 0) {
    uint64_t count = 0;
    // Count every entry except '.'/'..' — subdirectories included —
    // exactly like the reference's readdir loop (TFIDF.c:104-109).
    // directory_iterator already skips the two dot entries.
    for ([[maybe_unused]] auto& e :
         std::filesystem::directory_iterator(input_dir))
      ++count;
    std::memcpy(meta.data(), &count, 8);
  }
  comm.Broadcast(meta, 0);
  uint64_t num_docs;
  std::memcpy(&num_docs, meta.data(), 8);

  // Need at least one worker rank (the coordinator holds no documents —
  // a size-1 world would silently emit an empty output).
  if (size < 2) {
    if (rank == 0)
      std::fprintf(stderr, "error: need >=2 ranks (1 coordinator + workers)\n");
    return 1;
  }
  // Worker-count guard (TFIDF.c:120-123).
  if ((uint64_t)(size - 1) > num_docs) {
    if (rank == 0)
      std::fprintf(stderr,
                   "error: %d workers > %llu documents (reference guard)\n",
                   size - 1, (unsigned long long)num_docs);
    return 1;
  }

  // Phase 1: map/TF on workers over the round-robin shard (TFIDF.c:130).
  // The hybrid (-fopenmp) build adds intra-rank thread fan-out over the
  // rank's documents — the reference's OpenMP intent (TFIDF_extra.c:131)
  // done race-free: every document fills its own pre-sized slot and the
  // fold below is serial in document order, so hybrid and plain builds
  // are byte-identical (unlike the reference, whose shared-counter races
  // make its hybrid variant undefined, SURVEY §2.5-8).
  std::vector<Record> records;
  DfTable local_df;
  if (rank > 0) {
    std::vector<uint64_t> my_docs;
    for (uint64_t i = rank; i <= num_docs; i += size - 1) my_docs.push_back(i);
    struct DocResult {
      std::vector<Record> recs;
      std::vector<std::string> order;  // first-appearance word order
    };
    std::vector<DocResult> results(my_docs.size());
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic)
#endif
    for (long long di = 0; di < (long long)my_docs.size(); ++di) {
      std::string name = "doc" + std::to_string(my_docs[di]);
      std::ifstream f(input_dir + "/" + name, std::ios::binary);
      if (!f) {
        // Hard exit like the reference (TFIDF.c:137). A plain return
        // would deadlock peers at the next collective in thread mode.
        std::fprintf(stderr, "error: cannot open %s/%s\n", input_dir.c_str(),
                     name.c_str());
        std::exit(2);
      }
      std::string data((std::istreambuf_iterator<char>(f)),
                       std::istreambuf_iterator<char>());
      auto toks = Tokenize(data);
      const int64_t doc_size = (int64_t)toks.size();

      // First-appearance-ordered TF counts (the reference's linear-probe
      // append table, TFIDF.c:150-167, replaced by a hash index).
      std::unordered_map<std::string, int64_t> counts;
      for (auto& w : toks) {
        auto it = counts.find(w);
        if (it == counts.end()) {
          counts.emplace(w, 1);
          results[di].order.push_back(w);
        } else {
          ++it->second;
        }
      }
      for (auto& w : results[di].order)
        results[di].recs.push_back(Record{name, w, counts[w], doc_size});
    }
    // Serial fold in document order: record order and DF insertion
    // order come out exactly as the serial loop would produce them.
    for (auto& dr : results) {
      records.insert(records.end(), dr.recs.begin(), dr.recs.end());
      // DF: one per word per doc — the currDoc dedup (TFIDF.c:171-188).
      for (auto& w : dr.order) local_df.Add(w, 1);
    }
  }

  // Phase 2: DF reduction to root + broadcast (TFIDF.c:215,220) — the
  // pair the TPU path collapses into one lax.psum.
  std::vector<uint8_t> df_wire = local_df.Serialize();
  comm.ReduceToRoot(df_wire, 0, MergeDf);
  comm.Broadcast(df_wire, 0);
  DfTable global_df = DfTable::Deserialize(df_wire);

  // Phase 3: join + score (TFIDF.c:227-246). Same double ops, same order:
  // TF = 1.0*count/docSize; IDF = log(1.0*numDocs/df); score = TF*IDF.
  // Hybrid build: per-record slots (the reference's scoring pragma,
  // TFIDF_extra.c:230, made race-free); serialization stays serial so
  // the wire bytes are order-identical.
  std::vector<std::string> lines(records.size());
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (long long ri = 0; ri < (long long)records.size(); ++ri) {
    const Record& r = records[ri];
    double tf = 1.0 * (double)r.count / (double)r.doc_size;
    int64_t df = global_df.doc_counts[global_df.index.at(r.word)];
    double idf = std::log(1.0 * (double)num_docs / (double)df);
    double score = tf * idf;
    char buf[64];
    int n = std::snprintf(buf, sizeof buf, "%.16f", score);
    lines[ri] = r.doc + "@" + r.word + "\t" + std::string(buf, n);
  }
  std::vector<uint8_t> lines_wire;
  PutU32(lines_wire, (uint32_t)records.size());
  for (auto& line : lines) PutStr(lines_wire, line);

  // Phase 4: gather -> sort -> emit (TFIDF.c:253-283).
  std::vector<std::vector<uint8_t>> gathered;
  comm.GatherVariable(lines_wire, 0, gathered);
  if (rank == 0) {
    std::vector<std::string> lines;
    for (int r = 1; r < size; ++r) {
      size_t off = 0;
      uint32_t n = GetU32(gathered[r], off);
      for (uint32_t i = 0; i < n; ++i) lines.push_back(GetStr(gathered[r], off));
    }
    // strcmp order (TFIDF.c:47-50,273): std::string < is byte-wise.
    std::sort(lines.begin(), lines.end());
    std::ofstream out(output_path, std::ios::binary);
    if (!out) {
      std::fprintf(stderr, "error: cannot write %s\n", output_path.c_str());
      std::exit(3);
    }
    for (auto& l : lines) out << l << "\n";
  }
  comm.Barrier();
  return 0;
}

}  // namespace
}  // namespace tfidf

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr,
                 "usage: %s <input_dir> <output_file> [nranks]\n", argv[0]);
    return 64;
  }
  const std::string input = argv[1], output = argv[2];

#ifdef TFIDF_HAVE_MPI
  MPI_Init(&argc, &argv);
  tfidf::Comm* comm = tfidf::CreateMpiComm();
  int rc = tfidf::PipelineMain(*comm, input, output);
  delete comm;
  MPI_Finalize();
  return rc;
#else
  int nranks = argc > 3 ? std::atoi(argv[3]) : 4;
  if (nranks < 2) nranks = 2;  // coordinator + >=1 worker
  // argv[4]: rank backend — "thread" (default) or "process" (fork +
  // socketpair, the reference's N-OS-process deployment model without
  // an MPI runtime; byte-identical output pinned by tests).
  const std::string backend = argc > 4 ? argv[4] : "thread";
  if (backend == "process")
    return tfidf::RunProcessRanks(nranks, [&](tfidf::Comm& c) {
      return tfidf::PipelineMain(c, input, output);
    });
  int rc = 0;
  tfidf::RunThreadRanks(nranks, [&](tfidf::Comm& c) {
    int r = tfidf::PipelineMain(c, input, output);
    if (c.rank() == 0) rc = r;
  });
  return rc;
#endif
}
