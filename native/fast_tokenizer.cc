// fast_tokenizer — C-ABI tokenize+hash kernel for the host loader.
//
// The loader's hot host-side loop is tokenize -> FNV-1a -> fold-to-vocab
// (the reference does this work token-at-a-time inside fscanf loops,
// TFIDF.c:142-167; our Python fallback is tfidf_tpu/ops/tokenize.py +
// hashing.py). This native version does one pass over the raw bytes and
// writes vocab ids directly — called from Python via ctypes
// (tfidf_tpu/io/fast_tokenizer.py), no pybind11 needed.
//
// Contract matches the Python implementation exactly (tests pin this):
//   * tokens = maximal runs of non-isspace bytes (fscanf "%s" semantics);
//   * id = fold64(FNV1a64(token, seed)) % vocab_size, where
//     fold64(h) = h ^ (h >> 32) — see ops/hashing.py::hash_to_vocab.

#include <cstdint>
#include <cstddef>

#include "tokenize_common.h"

using tfidf::IsSpace;

extern "C" {

// Count whitespace-delimited tokens in data[0..len).
int64_t tok_count(const uint8_t* data, int64_t len) {
  int64_t n = 0, i = 0;
  while (i < len) {
    while (i < len && IsSpace(data[i])) ++i;
    if (i < len) ++n;
    while (i < len && !IsSpace(data[i])) ++i;
  }
  return n;
}

// Tokenize+hash into out_ids (capacity max_out). Returns the number of
// tokens written (never more than max_out; call tok_count for sizing).
// truncate_at > 0 clips each token to that many bytes before hashing
// (the PipelineConfig.truncate_tokens_at knob).
int64_t tok_hash_ids(const uint8_t* data, int64_t len, uint64_t seed,
                     int64_t vocab_size, int64_t truncate_at,
                     int32_t* out_ids, int64_t max_out) {
  return tfidf::TokenizeHashInto(data, len, seed, vocab_size, truncate_at,
                                 out_ids, max_out);
}

// Token span extraction for EXACT-vocab mode: writes (offset, length)
// pairs so Python can slice token bytes without re-scanning.
int64_t tok_spans(const uint8_t* data, int64_t len, int64_t* out_off,
                  int64_t* out_len, int64_t max_out) {
  int64_t n = 0, i = 0;
  while (i < len && n < max_out) {
    while (i < len && IsSpace(data[i])) ++i;
    int64_t start = i;
    while (i < len && !IsSpace(data[i])) ++i;
    if (i == start) break;
    out_off[n] = start;
    out_len[n] = i - start;
    ++n;
  }
  return n;
}

}  // extern "C"
