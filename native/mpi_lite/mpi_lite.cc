// mpi_lite runtime: the MPI subset in mpi.h over pairwise AF_UNIX
// socketpairs created by mpirun_lite and inherited across exec.
//
// Wire protocol per (src, dst) channel: framed messages
// [u32 tag][u64 bytes][payload]. The calls this runtime serves
// (comm.cc MpiComm) are strictly ordered per channel — every Send has
// exactly one program-ordered matching Recv — so a frame's tag must
// equal the tag the receiver asked for; a mismatch is a protocol bug
// and aborts loudly rather than reordering. Collective tags live in a
// reserved range (< 0) so they cannot collide with point-to-point tags.
//
// Deadlock note: all collectives here are root-sequenced (root sends
// to or receives from peers one at a time; peers talk only to root),
// so channel buffers bound memory, not progress.

#include "mpi.h"

#include <cerrno>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unistd.h>
#include <vector>

namespace {

constexpr int kTagBcast = -101;
constexpr int kTagBarrierIn = -102;
constexpr int kTagBarrierOut = -103;

struct World {
  int rank = 0;
  int size = 1;
  std::vector<int> fds;  // fds[r] = channel to rank r; own slot -1
  bool inited = false;
};

World g_world;

[[noreturn]] void Die(const char* what) {
  std::fprintf(stderr, "mpi_lite[rank %d]: %s (errno=%d %s)\n",
               g_world.rank, what, errno, std::strerror(errno));
  std::abort();
}

void WriteAll(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n) {
    ssize_t w = ::write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      Die("write failed");
    }
    p += w;
    n -= (size_t)w;
  }
}

void ReadAll(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r < 0) {
      if (errno == EINTR) continue;
      Die("read failed");
    }
    if (r == 0) Die("peer closed channel mid-message");
    p += r;
    n -= (size_t)r;
  }
}

void SendFrame(int peer, int tag, const void* data, uint64_t n) {
  int fd = g_world.fds[(size_t)peer];
  if (fd < 0) Die("send to self/unwired peer");
  int32_t t = (int32_t)tag;
  WriteAll(fd, &t, sizeof t);
  WriteAll(fd, &n, sizeof n);
  if (n) WriteAll(fd, data, (size_t)n);
}

uint64_t RecvFrame(int peer, int tag, void* data, uint64_t cap) {
  int fd = g_world.fds[(size_t)peer];
  if (fd < 0) Die("recv from self/unwired peer");
  int32_t t;
  uint64_t n;
  ReadAll(fd, &t, sizeof t);
  ReadAll(fd, &n, sizeof n);
  if (t != (int32_t)tag) Die("tag mismatch (out-of-order protocol)");
  if (n > cap) Die("frame larger than receive buffer");
  if (n) ReadAll(fd, data, (size_t)n);
  return n;
}

size_t DtypeSize(MPI_Datatype d) {
  switch (d) {
    case MPI_BYTE: return 1;
    case MPI_UINT64_T: return 8;
    default: Die("unsupported datatype");
  }
}

}  // namespace

extern "C" {

int MPI_Init(int*, char***) {
  const char* rank_s = std::getenv("MPILITE_RANK");
  const char* size_s = std::getenv("MPILITE_SIZE");
  const char* fds_s = std::getenv("MPILITE_FDS");
  if (!rank_s || !size_s || !fds_s) {
    std::fprintf(stderr,
                 "mpi_lite: not launched by mpirun_lite (MPILITE_* env "
                 "missing); run: mpirun_lite -np N <prog> <args...>\n");
    std::exit(2);
  }
  g_world.rank = std::atoi(rank_s);
  g_world.size = std::atoi(size_s);
  g_world.fds.clear();
  std::string s(fds_s);
  size_t pos = 0;
  while (pos <= s.size()) {
    size_t c = s.find(',', pos);
    if (c == std::string::npos) c = s.size();
    // strtol with end-pointer validation (advisor r5): atoi turns a
    // malformed entry ("x", "", "3x") into 0 — i.e. an innocent-looking
    // fd 0 that later reads stdin. A launcher bug must die HERE, named.
    const std::string tok = s.substr(pos, c - pos);
    char* end = nullptr;
    errno = 0;
    long fd = std::strtol(tok.c_str(), &end, 10);
    if (tok.empty() || end == tok.c_str() || *end != '\0' || errno != 0 ||
        fd < -1 || fd > INT_MAX) {
      std::fprintf(stderr,
                   "mpi_lite: malformed MPILITE_FDS entry '%s' in '%s'\n",
                   tok.c_str(), fds_s);
      std::exit(2);
    }
    g_world.fds.push_back((int)fd);
    pos = c + 1;
  }
  if ((int)g_world.fds.size() != g_world.size)
    Die("MPILITE_FDS length != MPILITE_SIZE");
  g_world.inited = true;
  return MPI_SUCCESS;
}

int MPI_Finalize(void) {
  for (int fd : g_world.fds)
    if (fd >= 0) ::close(fd);
  g_world.fds.clear();
  g_world.inited = false;
  return MPI_SUCCESS;
}

int MPI_Comm_rank(MPI_Comm, int* rank) {
  *rank = g_world.rank;
  return MPI_SUCCESS;
}

int MPI_Comm_size(MPI_Comm, int* size) {
  *size = g_world.size;
  return MPI_SUCCESS;
}

int MPI_Bcast(void* buf, int count, MPI_Datatype dtype, int root,
              MPI_Comm) {
  const uint64_t bytes = (uint64_t)count * DtypeSize(dtype);
  if (g_world.size == 1) return MPI_SUCCESS;
  if (g_world.rank == root) {
    for (int r = 0; r < g_world.size; ++r)
      if (r != root) SendFrame(r, kTagBcast, buf, bytes);
  } else {
    uint64_t n = RecvFrame(root, kTagBcast, buf, bytes);
    if (n != bytes) Die("bcast size mismatch");
  }
  return MPI_SUCCESS;
}

int MPI_Send(const void* buf, int count, MPI_Datatype dtype, int dest,
             int tag, MPI_Comm) {
  if (tag < 0) Die("negative tags are reserved for collectives");
  SendFrame(dest, tag, buf, (uint64_t)count * DtypeSize(dtype));
  return MPI_SUCCESS;
}

int MPI_Recv(void* buf, int count, MPI_Datatype dtype, int source,
             int tag, MPI_Comm, MPI_Status*) {
  if (tag < 0) Die("negative tags are reserved for collectives");
  RecvFrame(source, tag, buf, (uint64_t)count * DtypeSize(dtype));
  return MPI_SUCCESS;
}

int MPI_Barrier(MPI_Comm) {
  if (g_world.size == 1) return MPI_SUCCESS;
  uint8_t token = 0;
  if (g_world.rank == 0) {
    for (int r = 1; r < g_world.size; ++r)
      RecvFrame(r, kTagBarrierIn, &token, 1);
    for (int r = 1; r < g_world.size; ++r)
      SendFrame(r, kTagBarrierOut, &token, 1);
  } else {
    SendFrame(0, kTagBarrierIn, &token, 1);
    RecvFrame(0, kTagBarrierOut, &token, 1);
  }
  return MPI_SUCCESS;
}

}  // extern "C"
