// mpirun_lite — single-node process launcher for mpi_lite.
//
//   mpirun_lite -np N <prog> [args...]
//
// Creates one AF_UNIX socketpair per rank pair (i, j), forks N
// children, and execs <prog> in each with:
//   MPILITE_RANK=<r> MPILITE_SIZE=<N>
//   MPILITE_FDS=<fd to rank 0>,<fd to rank 1>,... (own slot -1)
// Children inherit only their own row's fds (everything else closed),
// so the runtime's channels are private pairwise pipes — the same
// process model as `mpirun -np N ./TFIDF` (TFIDF.c:82-92), minus the
// network. Exit status: 0 iff every rank exits 0.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

int main(int argc, char** argv) {
  int np = 0, argi = 1;
  if (argc >= 3 && std::strcmp(argv[1], "-np") == 0) {
    np = std::atoi(argv[2]);
    argi = 3;
  }
  if (np < 1 || argi >= argc) {
    std::fprintf(stderr, "usage: %s -np N <prog> [args...]\n", argv[0]);
    return 2;
  }

  // pair_fd[i][j] = fd rank i uses to talk to rank j (i != j).
  std::vector<std::vector<int>> pair_fd((size_t)np,
                                        std::vector<int>((size_t)np, -1));
  for (int i = 0; i < np; ++i)
    for (int j = i + 1; j < np; ++j) {
      int sv[2];
      if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
        std::perror("socketpair");
        return 2;
      }
      pair_fd[(size_t)i][(size_t)j] = sv[0];
      pair_fd[(size_t)j][(size_t)i] = sv[1];
    }

  std::vector<pid_t> kids((size_t)np);
  for (int r = 0; r < np; ++r) {
    pid_t pid = fork();
    if (pid < 0) {
      std::perror("fork");
      return 2;
    }
    if (pid == 0) {
      // Child rank r: keep row r, close every other pair's fds.
      for (int i = 0; i < np; ++i)
        for (int j = 0; j < np; ++j)
          if (i != r && j != r && pair_fd[(size_t)i][(size_t)j] >= 0 &&
              i < j) {
            close(pair_fd[(size_t)i][(size_t)j]);
            close(pair_fd[(size_t)j][(size_t)i]);
          }
      for (int j = 0; j < np; ++j)
        if (j != r) close(pair_fd[(size_t)j][(size_t)r]);
      std::string fds;
      for (int j = 0; j < np; ++j) {
        if (j) fds += ',';
        fds += std::to_string(pair_fd[(size_t)r][(size_t)j]);
      }
      setenv("MPILITE_RANK", std::to_string(r).c_str(), 1);
      setenv("MPILITE_SIZE", std::to_string(np).c_str(), 1);
      setenv("MPILITE_FDS", fds.c_str(), 1);
      execvp(argv[argi], argv + argi);
      std::perror("execvp");
      _exit(127);
    }
    kids[(size_t)r] = pid;
  }
  // Parent: close every fd, reap every rank.
  for (int i = 0; i < np; ++i)
    for (int j = i + 1; j < np; ++j) {
      close(pair_fd[(size_t)i][(size_t)j]);
      close(pair_fd[(size_t)j][(size_t)i]);
    }
  int rc = 0;
  for (int r = 0; r < np; ++r) {
    int st = 0;
    if (waitpid(kids[(size_t)r], &st, 0) < 0) rc = 2;
    else if (!WIFEXITED(st) || WEXITSTATUS(st) != 0) {
      std::fprintf(stderr, "mpirun_lite: rank %d exited abnormally\n", r);
      rc = WIFEXITED(st) ? WEXITSTATUS(st) : 2;
    }
  }
  return rc;
}
