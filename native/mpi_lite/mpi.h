// mpi_lite — a minimal, RUNNABLE single-node MPI runtime for the
// MPI-2 subset the TFIDF_HAVE_MPI code path uses (comm.cc MpiComm,
// tfidf_ref.cc main): Init/Finalize, Comm_rank/size, Bcast, Send,
// Recv, Barrier over MPI_COMM_WORLD with MPI_BYTE / MPI_UINT64_T.
//
// Unlike ../mpi_stub/mpi.h (compile-check only, aborts on call), this
// is a real implementation: ranks are OS processes launched by
// `mpirun_lite -np N prog args...`, wired pairwise with AF_UNIX
// socketpairs inherited across exec (fd table in MPILITE_FDS). The
// point is VERDICT r4 item 8: `mpirun -np N ./TFIDF` is the
// reference's actual deployment (TFIDF.c:82-92, Makefile_extra:10),
// and the MPI code path must be executed somewhere, not only
// type-checked. On a cluster with a real MPI, `make mpi` (mpicxx)
// still takes precedence — this header is only on the include path of
// the `make mpi_lite` target.
#ifndef TFIDF_MPI_LITE_H_
#define TFIDF_MPI_LITE_H_

typedef int MPI_Comm;
typedef int MPI_Datatype;
typedef struct MPI_Status_s { int ignored; } MPI_Status;

#define MPI_COMM_WORLD 0
#define MPI_BYTE 1
#define MPI_UINT64_T 2
#define MPI_STATUS_IGNORE ((MPI_Status*)0)
#define MPI_SUCCESS 0

#ifdef __cplusplus
extern "C" {
#endif

int MPI_Init(int* argc, char*** argv);
int MPI_Finalize(void);
int MPI_Comm_rank(MPI_Comm comm, int* rank);
int MPI_Comm_size(MPI_Comm comm, int* size);
int MPI_Bcast(void* buf, int count, MPI_Datatype dtype, int root,
              MPI_Comm comm);
int MPI_Send(const void* buf, int count, MPI_Datatype dtype, int dest,
             int tag, MPI_Comm comm);
int MPI_Recv(void* buf, int count, MPI_Datatype dtype, int source,
             int tag, MPI_Comm comm, MPI_Status* status);
int MPI_Barrier(MPI_Comm comm);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // TFIDF_MPI_LITE_H_
