// Minimal collective-communication abstraction for the native
// bit-reference runtime.
//
// The reference talks raw MPI over MPI_COMM_WORLD (SURVEY §2.4:
// Bcast/Reduce/Send/Recv/Barrier, TFIDF.c:82-325). This layer keeps the
// same collective *semantics* behind an interface with two backends:
//
//   * ThreadComm — ranks are threads in one process, collectives are
//     shared-memory + barrier. Runs anywhere (this box has no libmpi);
//     also the TSAN target for race testing (the reference's OpenMP
//     variant is racy, SURVEY §2.5-8 — ours must not be).
//   * MpiComm   — thin wrapper over real MPI, compiled when TFIDF_HAVE_MPI
//     is defined (see Makefile). Gives multi-node parity with the
//     reference's deployment model.
//
// Unlike the reference there is no derived-datatype wire format (the
// 44-vs-40-byte extent bug of TFIDF.c:78-89 is not reproducible here by
// construction): payloads are plain byte spans.

#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>
#include <vector>

namespace tfidf {

// A user-defined reduction over opaque accumulator blobs, applied
// pairwise: merge(src, dst) folds src into dst. The reference's
// CustomReduce (TFIDF.c:291-319) is one instance of this.
using MergeFn = std::function<void(const std::vector<uint8_t>& src,
                                   std::vector<uint8_t>& dst)>;

class Comm {
 public:
  virtual ~Comm() = default;

  virtual int rank() const = 0;
  virtual int size() const = 0;

  // Replicate root's buffer to all ranks (MPI_Bcast analog, TFIDF.c:115,220).
  virtual void Broadcast(std::vector<uint8_t>& buf, int root) = 0;

  // Fold every rank's contribution into rank root's accumulator with a
  // user merge (MPI_Op_create + MPI_Reduce analog, TFIDF.c:323-325).
  // Deterministic rank order 1,2,...,N-1 into root's copy: the
  // reference declares its op non-commutative (commute=0, TFIDF.c:324),
  // so ordered folding reproduces its insert-order tie-breaking.
  virtual void ReduceToRoot(std::vector<uint8_t>& buf, int root,
                            const MergeFn& merge) = 0;

  // Collect each rank's variable-size payload at root, rank order
  // (MPI_Send/Recv gather analog, TFIDF.c:256-270).
  virtual void GatherVariable(const std::vector<uint8_t>& payload, int root,
                              std::vector<std::vector<uint8_t>>& out) = 0;

  // Phase fence (MPI_Barrier analog, TFIDF.c:112 etc.).
  virtual void Barrier() = 0;
};

// Run `body(comm)` once per rank on `nranks` ranks using the thread
// backend. Blocks until all ranks finish.
void RunThreadRanks(int nranks, const std::function<void(Comm&)>& body);

// Run `body(comm)` once per rank on `nranks` OS PROCESSES (fork +
// socketpair star with rank 0 as hub) — the reference's actual
// deployment model (N processes under mpirun, TFIDF.c:82-92) without
// needing an MPI runtime in the image. Length-prefixed byte frames on
// the wire, like MpiComm. Rank 0 runs in the calling process; returns
// its body's view of completion (non-zero if any child exited
// non-zero). POSIX only.
int RunProcessRanks(int nranks, const std::function<int(Comm&)>& body);

#ifdef TFIDF_HAVE_MPI
// MPI-backed Comm for real multi-process runs; caller owns MPI_Init.
Comm* CreateMpiComm();
#endif

}  // namespace tfidf
