// Compile-check stub of the MPI-2 subset the TFIDF_HAVE_MPI code path
// uses (comm.cc:98-174, tfidf_ref.cc main). This environment ships no
// MPI implementation (`mpicxx` absent), which left the MPI backend as
// never-compiled dead code (VERDICT r1 "missing" item 4). Building
// against this stub (`make mpi_check`) type-checks every MPI call site
// on every test run, so the real `make mpi` build cannot silently rot.
//
// NOT a runtime: every function aborts if actually called. The real
// build must use a real <mpi.h> (mpicxx's include path wins because
// this directory is only added by the mpi_check target).
#ifndef TFIDF_MPI_STUB_H_
#define TFIDF_MPI_STUB_H_

#include <cstdlib>

typedef int MPI_Comm;
typedef int MPI_Datatype;
typedef struct MPI_Status_s { int ignored; } MPI_Status;

#define MPI_COMM_WORLD 0
#define MPI_BYTE 1
#define MPI_UINT64_T 2
#define MPI_STATUS_IGNORE ((MPI_Status*)nullptr)
#define MPI_SUCCESS 0

// The stub aborts on use: linking it is fine, running it is a bug.
inline int MPI_Stub_Abort_() { std::abort(); }

inline int MPI_Init(int*, char***) { return MPI_Stub_Abort_(); }
inline int MPI_Finalize() { return MPI_Stub_Abort_(); }
inline int MPI_Comm_rank(MPI_Comm, int*) { return MPI_Stub_Abort_(); }
inline int MPI_Comm_size(MPI_Comm, int*) { return MPI_Stub_Abort_(); }
inline int MPI_Bcast(void*, int, MPI_Datatype, int, MPI_Comm) {
  return MPI_Stub_Abort_();
}
inline int MPI_Send(const void*, int, MPI_Datatype, int, int, MPI_Comm) {
  return MPI_Stub_Abort_();
}
inline int MPI_Recv(void*, int, MPI_Datatype, int, int, MPI_Comm,
                    MPI_Status*) {
  return MPI_Stub_Abort_();
}
inline int MPI_Barrier(MPI_Comm) { return MPI_Stub_Abort_(); }

#endif  // TFIDF_MPI_STUB_H_
