// intern — global exact word-id table for the exact-terms fast path.
//
// The hashed pipeline's exact-terms mode pays a full host re-pass over
// the corpus (native/rerank.cc) because hash buckets merge words. This
// table removes the merging instead: during ingest the packer assigns
// every distinct token a dense EXACT id (first-seen order) shared
// across all chunks of a run, so the device's integer counts, DF, and
// top-k selection are word-exact by construction — the reference's
// string-keyed table semantics (TFIDF.c:26-42) with O(1) interning
// instead of its O(V_doc) linear probes (TFIDF.c:150-167). The host
// then rescores the selected candidates in float64 from integers alone
// and never touches document bytes again (tfidf_tpu/rerank.py).
//
// Capacity contract: at most `cap` distinct words (the device vocab);
// one past it sets the overflow flag and the fill aborts — the caller
// falls back to the hashed+margin+rerank engine. Concurrency: lock-free
// reads (acquire loads on the slot array; entries are preallocated so
// addresses never move), appends under a mutex — inserts are rare after
// the first few thousand tokens of a corpus.
//
// C ABI (ctypes from tfidf_tpu/io/fast_tokenizer.py):
//   intern_open(cap) -> handle
//   intern_fill_flat_u16(loader_h, intern_h, seed, trunc, max_per_doc,
//                        out, out_lengths) -> total ids or -1 overflow
//   intern_count(h) / intern_overflow(h)
//   intern_blob_bytes(h) / intern_dump(h, offs, lens, blob)
//   intern_close(h)

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <string_view>
#include <unordered_map>
#include <utility>
#include <vector>

#include "tokenize_common.h"

// Defined in loader.cc: borrow read-only views of the loaded docs.
extern "C" int64_t loader_doc_count(void* handle);
extern "C" const char* loader_doc_data(void* handle, int64_t d,
                                       int64_t* len);

namespace {

struct InternTable {
  struct Entry {
    uint64_t h;
    const char* w;
    int32_t len;
  };
  std::vector<Entry> entries;  // resized to cap once — stable addresses
  std::unique_ptr<std::atomic<int64_t>[]> slots;  // entry idx+1; 0=empty
  size_t mask = 0;
  int64_t cap = 0;
  std::mutex mu;               // guards arena + entry append
  std::deque<std::string> arena;  // owns word bytes (deque: stable)
  std::atomic<int64_t> live{0};
  std::atomic<int> overflow{0};
};

// Find-or-insert; returns the word's dense id, or -1 on overflow.
int64_t FindOrInsert(InternTable* T, uint64_t h, const uint8_t* w,
                     int64_t wl) {
  size_t s = (size_t)h & T->mask;
  for (;;) {
    int64_t e = T->slots[s].load(std::memory_order_acquire);
    if (e == 0) {
      std::lock_guard<std::mutex> lk(T->mu);
      e = T->slots[s].load(std::memory_order_relaxed);
      if (e == 0) {
        int64_t id = T->live.load(std::memory_order_relaxed);
        if (id >= T->cap) {
          T->overflow.store(1, std::memory_order_relaxed);
          return -1;
        }
        T->arena.emplace_back(reinterpret_cast<const char*>(w),
                              (size_t)wl);
        T->entries[(size_t)id] = {h, T->arena.back().data(), (int32_t)wl};
        T->live.store(id + 1, std::memory_order_relaxed);
        T->slots[s].store(id + 1, std::memory_order_release);
        return id;
      }
      // Another thread claimed the slot between our load and the lock:
      // fall through and compare against what it stored.
    }
    const InternTable::Entry& E = T->entries[(size_t)(e - 1)];
    if (E.h == h && E.len == (int32_t)wl &&
        std::memcmp(E.w, w, (size_t)wl) == 0)
      return e - 1;
    s = (s + 1) & T->mask;
  }
}

// Shared body of the exact-id flat packers (u16 / i32 wires): serial
// like loader_fill_flat_u16 — each doc's offset depends on every prior
// doc's count. Returns total ids, or -1 on vocab overflow.
template <typename T>
int64_t InternFillFlat(void* loader_handle, void* intern_handle,
                       uint64_t seed, int64_t truncate_at,
                       int64_t max_per_doc, T* out,
                       int32_t* out_lengths, int64_t align) {
  InternTable* tab = static_cast<InternTable*>(intern_handle);
  const int64_t n_docs = loader_doc_count(loader_handle);
  int64_t pos = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    int64_t len;
    const char* data = loader_doc_data(loader_handle, d, &len);
    bool bad = false;
    int64_t n = tfidf::ForEachToken(
        reinterpret_cast<const uint8_t*>(data), len, truncate_at,
        max_per_doc, [&](const uint8_t* w, int64_t wl) {
          int64_t id =
              FindOrInsert(tab, tfidf::HashWordRaw(w, wl, seed), w, wl);
          if (id < 0) {
            bad = true;
            return;
          }
          out[pos++] = (T)id;
        });
    if (bad) return -1;
    out_lengths[d] = (int32_t)n;
    if (align > 1) {  // granule-aligned wire (see loader.cc)
      int64_t pad = (align - pos % align) % align;
      std::memset(out + pos, 0, (size_t)pad * sizeof(T));
      pos += pad;
    }
  }
  return pos;
}

}  // namespace

extern "C" {

void* intern_open(int64_t cap) {
  InternTable* T = new InternTable;
  T->cap = cap;
  size_t n = 1;
  while (n < (size_t)cap * 2) n <<= 1;  // load factor <= 0.5
  T->slots.reset(new std::atomic<int64_t>[n]);
  for (size_t i = 0; i < n; ++i)
    T->slots[i].store(0, std::memory_order_relaxed);
  T->mask = n - 1;
  T->entries.resize((size_t)cap);
  return T;
}

// Exact-id flat pack over a loader handle's docs: the exact-mode twin
// of loader_fill_flat_u16 (same serial flat-wire contract), with the
// hash fold replaced by interning. Returns total ids written, or -1 on
// vocab overflow (out/out_lengths contents are then unspecified).
int64_t intern_fill_flat_u16(void* loader_handle, void* intern_handle,
                             uint64_t seed, int64_t truncate_at,
                             int64_t max_per_doc, uint16_t* out,
                             int32_t* out_lengths, int64_t align) {
  return InternFillFlat(loader_handle, intern_handle, seed, truncate_at,
                        max_per_doc, out, out_lengths, align);
}

// int32 wire for vocab caps past 2^16 (wide-vocab exact mode).
int64_t intern_fill_flat_i32(void* loader_handle, void* intern_handle,
                             uint64_t seed, int64_t truncate_at,
                             int64_t max_per_doc, int32_t* out,
                             int32_t* out_lengths, int64_t align) {
  return InternFillFlat(loader_handle, intern_handle, seed, truncate_at,
                        max_per_doc, out, out_lengths, align);
}

int64_t intern_count(void* handle) {
  return static_cast<InternTable*>(handle)->live.load();
}

int intern_overflow(void* handle) {
  return static_cast<InternTable*>(handle)->overflow.load();
}

int64_t intern_blob_bytes(void* handle) {
  InternTable* T = static_cast<InternTable*>(handle);
  int64_t n = T->live.load(), bytes = 0;
  for (int64_t i = 0; i < n; ++i) bytes += T->entries[(size_t)i].len;
  return bytes;
}

// Dump the id -> word dictionary: offs/lens [count], blob packed bytes.
void intern_dump(void* handle, int64_t* offs, int64_t* lens, char* blob) {
  InternTable* T = static_cast<InternTable*>(handle);
  int64_t n = T->live.load(), pos = 0;
  for (int64_t i = 0; i < n; ++i) {
    const InternTable::Entry& e = T->entries[(size_t)i];
    offs[i] = pos;
    lens[i] = e.len;
    std::memcpy(blob + pos, e.w, (size_t)e.len);
    pos += e.len;
  }
}

void intern_close(void* handle) {
  delete static_cast<InternTable*>(handle);
}

}  // extern "C"

// ---------------------------------------------------------------------
// exact_emit — the exact-terms finishing engine (rescore + format +
// global sort), the native twin of rerank.exact_topk_from_wire.
//
// Inputs are the exact-ids wire integers: per-doc (id, count)
// candidates, the [V] exact DF vector, truncated docSizes. Per doc:
// float64 TF-IDF in the reference's op order (TFIDF.c:202,243), filter
// score > 0, sort (-score, word asc), keep k, format
// "name@word\t%.16f" — then ONE global byte-lex sort of all lines (the
// reference's qsort, TFIDF.c:273). Boundary-tie docs (full wire whose
// tail score ties the k-th entry — the word-asc choice is undecidable
// from the wire) are re-read and resolved exactly HERE, against the
// still-open intern table; no corpus scan.

namespace {

struct EmitResult {
  std::vector<int32_t> per_doc_counts;  // kept entries per doc
  std::vector<int64_t> offs, lens;      // word spans in word_blob
  std::vector<double> scores;           // doc-major kept scores
  std::string word_blob;
  std::string lines;                    // final sorted output bytes
};

bool ReadWholeFile(const std::string& path, std::string* out) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out->resize((size_t)sz);
  size_t got = sz ? std::fread(&(*out)[0], 1, (size_t)sz, f) : 0;
  std::fclose(f);
  return got == (size_t)sz;
}

// Read-only probe of the intern table (no insertion).
int64_t InternFind(InternTable* T, uint64_t h, const uint8_t* w,
                   int64_t wl) {
  size_t s = (size_t)h & T->mask;
  for (;;) {
    int64_t e = T->slots[s].load(std::memory_order_acquire);
    if (e == 0) return -1;
    const InternTable::Entry& E = T->entries[(size_t)(e - 1)];
    if (E.h == h && E.len == (int32_t)wl &&
        std::memcmp(E.w, w, (size_t)wl) == 0)
      return e - 1;
    s = (s + 1) & T->mask;
  }
}

struct ExactEntry {
  int32_t id;
  double score;
};

}  // namespace

extern "C" {

// Returns an EmitResult*, or null when a boundary-tie document could
// not be re-read (*out_failed_doc = its index) — the caller must fail
// loudly, exactly like the Python twin's FileNotFoundError: emitting
// the unresolved wire candidates would silently break the tie
// contract.
void* exact_emit_run(void* intern_handle, const char* input_dir,
                     const char* names_blob, const int32_t* ids,
                     const int32_t* counts, int64_t n_docs,
                     int64_t kprime, const int32_t* df,
                     int64_t vocab_size, const int32_t* lengths,
                     int64_t num_docs_idf, int64_t k, int64_t truncate_at,
                     int64_t max_tokens, uint64_t seed, int n_threads,
                     int64_t* out_failed_doc) {
  (void)vocab_size;
  InternTable* T = static_cast<InternTable*>(intern_handle);
  std::atomic<int64_t> failed{-1};
  std::vector<const char*> names(n_docs);
  {
    const char* p = names_blob;
    for (int64_t d = 0; d < n_docs; ++d) {
      names[d] = p;
      p += std::strlen(p) + 1;
    }
  }
  const double n_idf = (double)num_docs_idf;
  std::vector<std::vector<ExactEntry>> picked(n_docs);
  // TFIDF_EMIT_DEBUG=1: phase wall-clocks + tie-re-read count on
  // stderr — the measurement feed for the emit-tail work (VERDICT r4
  // item 5). Zero cost when unset.
  const bool debug = std::getenv("TFIDF_EMIT_DEBUG") != nullptr;
  std::atomic<int64_t> n_tied{0};
  auto now = [] {
    return std::chrono::duration<double>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  };
  double t0 = debug ? now() : 0.0;
  tfidf::ParallelFor(n_docs, n_threads, [&](int64_t d) {
    const int32_t* row_id = ids + d * kprime;
    const int32_t* row_cn = counts + d * kprime;
    const double len = lengths[d] > 0 ? (double)lengths[d] : 1.0;
    std::vector<ExactEntry> cand;
    cand.reserve((size_t)kprime);
    bool full = true;
    for (int64_t j = 0; j < kprime; ++j) {
      if (row_cn[j] <= 0) {
        full = false;
        continue;
      }
      double idf = std::log(n_idf / (double)df[row_id[j]]);
      cand.push_back({row_id[j], (double)row_cn[j] / len * idf});
    }
    auto by_score_word = [&](const ExactEntry& a, const ExactEntry& b) {
      if (a.score != b.score) return a.score > b.score;
      const InternTable::Entry &ea = T->entries[(size_t)a.id],
                               &eb = T->entries[(size_t)b.id];
      int c = std::memcmp(ea.w, eb.w,
                          (size_t)(ea.len < eb.len ? ea.len : eb.len));
      if (c != 0) return c < 0;
      return ea.len < eb.len;
    };
    std::sort(cand.begin(), cand.end(), by_score_word);
    int64_t kk = k < (int64_t)cand.size() ? k : (int64_t)cand.size();
    // Boundary tie: full wire and the tail's positive score ties the
    // k-th — resolve from the document itself (exactly the Python
    // rule, rerank.exact_topk_from_wire). Two refinements (advisor r4):
    //  * "ties" means within float32 rounding distance (4e-6 rel), not
    //    only exact f64 equality — the device ranked by float32, so a
    //    near-tie group can collapse there and be truncated in
    //    intern-id order even when the f64 scores are distinct;
    //  * a doc with lengths[d] <= kprime tokens cannot have more
    //    distinct terms than the wire holds — its full wire is the
    //    complete term set, so the heuristic must not fire (otherwise
    //    doc_len <= k degrades every dense doc to a re-read).
    bool tied = full && kprime > 0 && kk > 0 &&
                (int64_t)lengths[d] > kprime &&
                cand.back().score > 0.0 &&
                cand[(size_t)kk - 1].score - cand.back().score <=
                    cand[(size_t)kk - 1].score * 4e-6;
    if (tied) {
      n_tied.fetch_add(1, std::memory_order_relaxed);
      std::string path = std::string(input_dir) + "/" + names[d];
      std::string data;
      if (!ReadWholeFile(path, &data)) {
        int64_t expect = -1;
        failed.compare_exchange_strong(expect, d);
        return;
      }
      {
        // Exact doc-local recount: sort+RLE over (hash, bytes) like
        // rerank.cc pass 1, then score every distinct term.
        std::vector<tfidf::HashedTok> toks;
        int64_t size = tfidf::ForEachTokenView(
            data.data(), (int64_t)data.size(), truncate_at, max_tokens,
            [&](std::string_view w) {
              toks.push_back({tfidf::HashView(w, seed), w});
            });
        std::sort(toks.begin(), toks.end(), tfidf::HashedTokLess);
        cand.clear();
        const double dlen = size > 0 ? (double)size : 1.0;
        for (size_t i = 0; i < toks.size();) {
          size_t j = i + 1;
          while (j < toks.size() && toks[j].h == toks[i].h &&
                 toks[j].w == toks[i].w)
            ++j;
          int64_t id = InternFind(
              T, toks[i].h,
              reinterpret_cast<const uint8_t*>(toks[i].w.data()),
              (int64_t)toks[i].w.size());
          if (id >= 0) {
            double idf = std::log(n_idf / (double)df[id]);
            double s = (double)(j - i) / dlen * idf;
            if (s > 0.0) cand.push_back({(int32_t)id, s});
          }
          i = j;
        }
        std::sort(cand.begin(), cand.end(), by_score_word);
        kk = k < (int64_t)cand.size() ? k : (int64_t)cand.size();
      }
    }
    std::vector<ExactEntry>& out = picked[d];
    for (int64_t j = 0; j < kk && cand[(size_t)j].score > 0.0; ++j)
      out.push_back(cand[(size_t)j]);
  });

  double t_pick = debug ? now() - t0 : 0.0;
  if (failed.load() >= 0) {
    if (out_failed_doc) *out_failed_doc = failed.load();
    return nullptr;
  }
  if (out_failed_doc) *out_failed_doc = -1;

  // Assemble: doc-major entry arrays + the globally sorted line blob.
  EmitResult* res = new EmitResult;
  res->per_doc_counts.resize(n_docs);
  int64_t total = 0, wbytes = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    res->per_doc_counts[d] = (int32_t)picked[d].size();
    total += (int64_t)picked[d].size();
    for (const ExactEntry& e : picked[d])
      wbytes += T->entries[(size_t)e.id].len;
  }
  res->offs.reserve(total);
  res->lens.reserve(total);
  res->scores.reserve(total);
  res->word_blob.reserve(wbytes);

  // The reference's global line qsort (TFIDF.c:273) as an INTEGER key
  // sort: line byte-lex order == (rank of name+'@', rank of word)
  // because '@' precedes the name comparison exactly where the line
  // does, and '\t' (below every non-whitespace byte a word can hold)
  // makes plain word-lex agree with the line's word+'\t' segment. One
  // u64 key per line beats comparing 60-byte strings ~line-count times.
  //
  // The equivalence needs '@'-free names: with names "doc" and "doc@a"
  // the key ranks every "doc" line before every "doc@a" line, while
  // full-line bytes interleave them ("doc@a@beta" < "doc@xray").
  // Likewise it needs words free of bytes below '\t' (0x01-0x08, legal
  // token bytes): plain word-lex puts "a" before "a\x01x", but the
  // line segments order "a\x01x\t" before "a\t" since 0x01 < 0x09.
  // Reachable only via --no-strict / binary-ish corpora; such runs
  // take the assemble-and-sort-the-bytes fallback below (advisor r4).
  bool need_byte_sort = false;
  for (int64_t d = 0; d < n_docs && !need_byte_sort; ++d)
    if (std::strchr(names[d], '@') != nullptr) need_byte_sort = true;
  {
    const int64_t nlive = T->live.load();
    for (int64_t i = 0; i < nlive && !need_byte_sort; ++i) {
      const InternTable::Entry& E = T->entries[(size_t)i];
      for (int32_t b = 0; b < E.len; ++b)
        if ((unsigned char)E.w[b] < (unsigned char)'\t') {
          need_byte_sort = true;
          break;
        }
    }
  }
  std::vector<int32_t> name_rank(need_byte_sort ? 0 : (size_t)n_docs);
  if (!need_byte_sort) {
    std::vector<int32_t> order(n_docs);
    for (int64_t d = 0; d < n_docs; ++d) order[(size_t)d] = (int32_t)d;
    std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
      std::string ka = std::string(names[a]) + '@';
      std::string kb = std::string(names[b]) + '@';
      return ka < kb;
    });
    for (int64_t i = 0; i < n_docs; ++i)
      name_rank[(size_t)order[(size_t)i]] = (int32_t)i;
  }
  const int64_t live = T->live.load();
  std::vector<int32_t> word_rank(need_byte_sort ? 1 : (size_t)(live ? live : 1));
  if (!need_byte_sort) {
    std::vector<int32_t> order((size_t)live);
    for (int64_t i = 0; i < live; ++i) order[(size_t)i] = (int32_t)i;
    std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
      const InternTable::Entry &ea = T->entries[(size_t)a],
                               &eb = T->entries[(size_t)b];
      int c = std::memcmp(ea.w, eb.w,
                          (size_t)(ea.len < eb.len ? ea.len : eb.len));
      if (c != 0) return c < 0;
      return ea.len < eb.len;
    });
    for (int64_t i = 0; i < live; ++i)
      word_rank[(size_t)order[(size_t)i]] = (int32_t)i;
  }

  std::vector<std::pair<uint64_t, int64_t>> keyed;  // (key, entry no.)
  std::vector<std::string> line_strs;  // '@'-in-name fallback only
  std::vector<int32_t> entry_doc((size_t)(total ? total : 1));
  char buf[64];
  if (need_byte_sort)
    line_strs.reserve((size_t)total);
  else
    keyed.reserve(total);
  int64_t eno = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    for (const ExactEntry& e : picked[d]) {
      const InternTable::Entry& w = T->entries[(size_t)e.id];
      res->offs.push_back((int64_t)res->word_blob.size());
      res->lens.push_back(w.len);
      res->scores.push_back(e.score);
      res->word_blob.append(w.w, (size_t)w.len);
      entry_doc[(size_t)eno] = (int32_t)d;
      if (need_byte_sort) {
        std::string line(names[(size_t)d]);
        line.push_back('@');
        line.append(w.w, (size_t)w.len);
        line.push_back('\t');
        int m = std::snprintf(buf, sizeof buf, "%.16f", e.score);
        line.append(buf, (size_t)m);
        line_strs.push_back(std::move(line));
      } else {
        keyed.emplace_back(((uint64_t)(uint32_t)name_rank[(size_t)d] << 32)
                               | (uint32_t)word_rank[(size_t)e.id],
                           eno);
      }
      ++eno;
    }
  }
  res->lines.reserve((int64_t)total * 48);
  if (need_byte_sort) {
    // Full-line byte sort — the reference's qsort semantics verbatim,
    // correct for any name bytes (scores included in the compare,
    // matching TFIDF.c:273 when assembled prefixes collide).
    std::sort(line_strs.begin(), line_strs.end());
    for (const std::string& l : line_strs) {
      res->lines.append(l);
      res->lines.push_back('\n');
    }
    // The pick phase ran either way — without this the byte-sort
    // fallback returned before the debug line, so '@'-in-name corpora
    // silently dropped the pick timing and tie count (advisor r5).
    if (debug)
      std::fprintf(stderr,
                   "exact_emit: pick %.3fs (tied %lld/%lld) byte-sort "
                   "fallback total %.3fs\n",
                   t_pick, (long long)n_tied.load(), (long long)n_docs,
                   now() - t0);
    return res;
  }
  double t_rank = debug ? now() - t0 - t_pick : 0.0;
  std::sort(keyed.begin(), keyed.end());
  double t_sort = debug ? now() - t0 - t_pick - t_rank : 0.0;
  // Score-format memo: TF-IDF scores are functions of small integer
  // tuples (count, docSize, df, N), so a Zipf corpus repeats the same
  // double constantly — snprintf("%.16f") measured 0.22 s of the
  // 0.33 s emit at 32k docs (TFIDF_EMIT_DEBUG). Keyed by bit pattern:
  // equal bits => equal %.16f bytes, trivially.
  std::unordered_map<uint64_t, std::string> fmt_memo;
  fmt_memo.reserve(1 << 16);
  auto fmt_score = [&](double s) -> const std::string& {
    uint64_t bits;
    std::memcpy(&bits, &s, sizeof bits);
    auto it = fmt_memo.find(bits);
    if (it == fmt_memo.end()) {
      int m = std::snprintf(buf, sizeof buf, "%.16f", s);
      it = fmt_memo.emplace(bits, std::string(buf, (size_t)m)).first;
    }
    return it->second;
  };
  for (const auto& kv : keyed) {
    int64_t entry = kv.second;
    res->lines.append(names[(size_t)entry_doc[(size_t)entry]]);
    res->lines.push_back('@');
    res->lines.append(res->word_blob, (size_t)res->offs[(size_t)entry],
                      (size_t)res->lens[(size_t)entry]);
    res->lines.push_back('\t');
    res->lines.append(fmt_score(res->scores[(size_t)entry]));
    res->lines.push_back('\n');
  }
  if (debug)
    std::fprintf(stderr,
                 "exact_emit: pick %.3fs (tied %lld/%lld) rank+assemble "
                 "%.3fs keysort %.3fs format %.3fs total %.3fs\n",
                 t_pick, (long long)n_tied.load(), (long long)n_docs,
                 t_rank, t_sort, now() - t0 - t_pick - t_rank - t_sort,
                 now() - t0);
  return res;
}

int64_t exact_emit_total(void* res) {
  return (int64_t)static_cast<EmitResult*>(res)->scores.size();
}

int64_t exact_emit_word_bytes(void* res) {
  return (int64_t)static_cast<EmitResult*>(res)->word_blob.size();
}

int64_t exact_emit_line_bytes(void* res) {
  return (int64_t)static_cast<EmitResult*>(res)->lines.size();
}

void exact_emit_fill(void* res_p, int32_t* per_doc_counts, int64_t* offs,
                     int64_t* lens, double* scores, char* word_blob,
                     char* line_blob) {
  EmitResult* res = static_cast<EmitResult*>(res_p);
  std::memcpy(per_doc_counts, res->per_doc_counts.data(),
              res->per_doc_counts.size() * sizeof(int32_t));
  std::memcpy(offs, res->offs.data(), res->offs.size() * sizeof(int64_t));
  std::memcpy(lens, res->lens.data(), res->lens.size() * sizeof(int64_t));
  std::memcpy(scores, res->scores.data(),
              res->scores.size() * sizeof(double));
  std::memcpy(word_blob, res->word_blob.data(), res->word_blob.size());
  std::memcpy(line_blob, res->lines.data(), res->lines.size());
}

void exact_emit_free(void* res) { delete static_cast<EmitResult*>(res); }

}  // extern "C"
