// Shared tokenize+hash contract for the native runtime.
//
// Single source of truth for whitespace semantics and the FNV-1a64 ->
// xor-fold -> mod-vocab id function, used by fast_tokenizer.cc (per-doc
// ctypes kernels) and loader.cc (parallel corpus loader). The Python
// path (tfidf_tpu/ops/tokenize.py + hashing.py) is contract-identical;
// tests/test_native.py pins all of them against each other.

#ifndef TFIDF_NATIVE_TOKENIZE_COMMON_H_
#define TFIDF_NATIVE_TOKENIZE_COMMON_H_

#include <atomic>
#include <cstdint>
#include <string_view>
#include <thread>
#include <vector>

namespace tfidf {

// Work-stealing parallel-for over [0, n): threads pop the next index
// from a shared atomic — dynamic scheduling, so a few huge documents
// don't stall a static stripe (the reference's static round-robin
// schedule, TFIDF.c:130, has exactly that imbalance failure mode).
// Shared by loader.cc and rerank.cc.
template <typename Fn>
inline void ParallelFor(int64_t n, int n_threads, Fn fn) {
  if (n_threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next{0};
  auto worker = [&] {
    for (;;) {
      int64_t i = next.fetch_add(1, std::memory_order_relaxed);
      if (i >= n) return;
      fn(i);
    }
  };
  std::vector<std::thread> pool;
  int spawn = (int)(n_threads < n ? n_threads : n) - 1;
  pool.reserve(spawn);
  for (int t = 0; t < spawn; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
}

constexpr uint64_t kFnvOffset = 14695981039346656037ull;
constexpr uint64_t kFnvPrime = 1099511628211ull;

// Fixed ASCII whitespace set — the C-locale isspace set and exactly what
// Python bytes.split() uses. Deliberately NOT std::isspace, which is
// locale-dependent (CPython calls setlocale at startup, so the host
// locale could silently change token boundaries vs the Python path).
inline bool IsSpace(uint8_t c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\v' || c == '\f' ||
         c == '\r';
}

// THE hash, in two composable halves so no consumer ever re-implements
// either: HashWordRaw = seeded FNV-1a64 of the token bytes (a grouping
// key in its own right — rerank.cc); FoldToVocab = xor-fold + mod.
// HashWord = the composition; every native consumer (loader pack,
// rerank candidate matching) goes through these, so the contract
// cannot fork.
inline uint64_t HashWordRaw(const uint8_t* w, int64_t len, uint64_t seed) {
  uint64_t h = kFnvOffset ^ seed;
  for (int64_t j = 0; j < len; ++j) h = (h ^ w[j]) * kFnvPrime;
  return h;
}

inline int64_t FoldToVocab(uint64_t h, int64_t vocab_size) {
  h ^= h >> 32;
  return (int64_t)(h % (uint64_t)vocab_size);
}

inline int64_t HashWord(const uint8_t* w, int64_t len, uint64_t seed,
                        int64_t vocab_size) {
  return FoldToVocab(HashWordRaw(w, len, seed), vocab_size);
}

// Tokenize data[0..len): fn(ptr, len) per token, each truncated to
// truncate_at bytes when truncate_at > 0 (whitespace_tokenize parity),
// stopping after max_tokens when max_tokens > 0. Returns tokens seen.
// THE tokenizer loop; TokenizeHashInto and rerank.cc both ride it.
template <typename Fn>
inline int64_t ForEachToken(const uint8_t* data, int64_t len,
                            int64_t truncate_at, int64_t max_tokens,
                            Fn fn) {
  int64_t n = 0, i = 0;
  while (i < len && (max_tokens <= 0 || n < max_tokens)) {
    while (i < len && IsSpace(data[i])) ++i;
    int64_t start = i;
    while (i < len && !IsSpace(data[i])) ++i;
    if (i == start) break;
    int64_t end = i;
    if (truncate_at > 0 && end - start > truncate_at)
      end = start + truncate_at;
    fn(data + start, end - start);
    ++n;
  }
  return n;
}

// Tokenize data[0..len), hash each token (truncated to truncate_at bytes
// when truncate_at > 0) and write ids of integral type T into out
// (capacity max_out; excess tokens are dropped). Returns tokens written.
template <typename T>
inline int64_t TokenizeHashInto(const uint8_t* data, int64_t len,
                                uint64_t seed, int64_t vocab_size,
                                int64_t truncate_at, T* out,
                                int64_t max_out) {
  if (max_out <= 0) return 0;  // capacity contract: write nothing
  // (ForEachToken's max_tokens <= 0 means UNLIMITED — do not forward).
  return ForEachToken(data, len, truncate_at, max_out,
                      [&](const uint8_t* w, int64_t wl) {
                        *out++ = (T)HashWord(w, wl, seed, vocab_size);
                      });
}

// string_view-level adapters over the tokenizer loop + raw hash,
// shared by the exact engines (rerank.cc, intern.cc exact_emit): a
// (raw-hash, bytes) token key whose ordering groups equal words for
// sort+RLE counting. Exactness never rests on the hash alone — every
// hash-equal comparison is verified on bytes.
struct HashedTok {
  uint64_t h;
  std::string_view w;
};

inline bool HashedTokLess(const HashedTok& a, const HashedTok& b) {
  if (a.h != b.h) return a.h < b.h;
  return a.w < b.w;
}

inline uint64_t HashView(std::string_view w, uint64_t seed) {
  return HashWordRaw(reinterpret_cast<const uint8_t*>(w.data()),
                     (int64_t)w.size(), seed);
}

template <typename Fn>
inline int64_t ForEachTokenView(const char* data, int64_t len,
                                int64_t truncate_at, int64_t max_tokens,
                                Fn fn) {
  return ForEachToken(
      reinterpret_cast<const uint8_t*>(data), len, truncate_at,
      max_tokens, [&](const uint8_t* w, int64_t wl) {
        fn(std::string_view(reinterpret_cast<const char*>(w),
                            (size_t)wl));
      });
}

}  // namespace tfidf

#endif  // TFIDF_NATIVE_TOKENIZE_COMMON_H_
