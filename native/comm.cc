// Thread-backed (and optional MPI-backed) implementations of the Comm
// interface declared in comm.h.

#include "comm.h"

#include <condition_variable>
#include <mutex>
#include <thread>

namespace tfidf {
namespace {

// Shared state for one thread-“cluster”. A generation-counted barrier
// plus a mailbox table; every collective is fenced by barriers on both
// sides, so one mailbox slot per rank suffices.
struct ThreadWorld {
  explicit ThreadWorld(int n) : nranks(n), mailbox(n) {}

  const int nranks;
  std::mutex mu;
  std::condition_variable cv;
  int arrived = 0;
  uint64_t generation = 0;
  std::vector<std::vector<uint8_t>> mailbox;

  void Barrier() {
    std::unique_lock<std::mutex> lock(mu);
    const uint64_t gen = generation;
    if (++arrived == nranks) {
      arrived = 0;
      ++generation;
      cv.notify_all();
    } else {
      cv.wait(lock, [&] { return generation != gen; });
    }
  }
};

class ThreadComm : public Comm {
 public:
  ThreadComm(ThreadWorld* world, int rank) : world_(world), rank_(rank) {}

  int rank() const override { return rank_; }
  int size() const override { return world_->nranks; }

  void Broadcast(std::vector<uint8_t>& buf, int root) override {
    if (rank_ == root) world_->mailbox[root] = buf;
    world_->Barrier();  // publish
    if (rank_ != root) buf = world_->mailbox[root];
    world_->Barrier();  // consume before root reuses the slot
  }

  void ReduceToRoot(std::vector<uint8_t>& buf, int root,
                    const MergeFn& merge) override {
    world_->mailbox[rank_] = buf;
    world_->Barrier();  // all contributions published
    if (rank_ == root) {
      for (int r = 0; r < world_->nranks; ++r) {
        if (r == root) continue;
        merge(world_->mailbox[r], buf);  // deterministic rank order
      }
    }
    world_->Barrier();  // merges done before slots are reused
  }

  void GatherVariable(const std::vector<uint8_t>& payload, int root,
                      std::vector<std::vector<uint8_t>>& out) override {
    world_->mailbox[rank_] = payload;
    world_->Barrier();
    if (rank_ == root) out = world_->mailbox;
    world_->Barrier();
  }

  void Barrier() override { world_->Barrier(); }

 private:
  ThreadWorld* world_;
  int rank_;
};

}  // namespace

void RunThreadRanks(int nranks, const std::function<void(Comm&)>& body) {
  ThreadWorld world(nranks);
  std::vector<std::thread> threads;
  threads.reserve(nranks);
  for (int r = 0; r < nranks; ++r) {
    threads.emplace_back([&world, r, &body] {
      ThreadComm comm(&world, r);
      body(comm);
    });
  }
  for (auto& t : threads) t.join();
}

}  // namespace tfidf

// ---------------------------------------------------------------------
// Process backend: fork + socketpair star, rank 0 as hub. The
// reference's deployment model is N OS processes under mpirun
// (TFIDF.c:82-92); this backend actually EXECUTES that model on a
// machine with no MPI runtime. Every collective is root-centric in the
// pipeline, so a star topology suffices; non-hub roots are served by
// relaying through the hub. Frames are length-prefixed byte spans,
// the same wire discipline as MpiComm (no derived-datatype extent bug
// by construction, SURVEY §2.5-2).

#include <sys/socket.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>

namespace tfidf {
namespace {

void WriteAll(int fd, const void* p, size_t n) {
  const uint8_t* b = static_cast<const uint8_t*>(p);
  while (n) {
    ssize_t w = ::write(fd, b, n);
    if (w <= 0) {
      std::perror("comm write");
      std::abort();  // a dead peer hangs the reference too (SURVEY §5)
    }
    b += w;
    n -= (size_t)w;
  }
}

void ReadAll(int fd, void* p, size_t n) {
  uint8_t* b = static_cast<uint8_t*>(p);
  while (n) {
    ssize_t r = ::read(fd, b, n);
    if (r <= 0) {
      std::perror("comm read");
      std::abort();
    }
    b += r;
    n -= (size_t)r;
  }
}

void SendFrame(int fd, const std::vector<uint8_t>& buf) {
  uint64_t n = buf.size();
  WriteAll(fd, &n, sizeof n);
  if (n) WriteAll(fd, buf.data(), n);
}

std::vector<uint8_t> RecvFrame(int fd) {
  uint64_t n = 0;
  ReadAll(fd, &n, sizeof n);
  std::vector<uint8_t> buf(n);
  if (n) ReadAll(fd, buf.data(), n);
  return buf;
}

class ProcessComm : public Comm {
 public:
  // Hub: fds[r] = socket to rank r (fds[0] unused). Spoke: fd to hub.
  ProcessComm(int rank, int nranks, std::vector<int> hub_fds, int spoke_fd)
      : rank_(rank), nranks_(nranks), fds_(std::move(hub_fds)),
        fd_(spoke_fd) {}

  int rank() const override { return rank_; }
  int size() const override { return nranks_; }

  void Broadcast(std::vector<uint8_t>& buf, int root) override {
    if (rank_ == 0) {
      if (root != 0) buf = RecvFrame(fds_[root]);
      for (int r = 1; r < nranks_; ++r)
        if (r != root) SendFrame(fds_[r], buf);
    } else if (rank_ == root) {
      SendFrame(fd_, buf);
    } else {
      buf = RecvFrame(fd_);
    }
  }

  void GatherVariable(const std::vector<uint8_t>& payload, int root,
                      std::vector<std::vector<uint8_t>>& out) override {
    if (rank_ == 0) {
      std::vector<std::vector<uint8_t>> all(nranks_);
      all[0] = payload;
      for (int r = 1; r < nranks_; ++r) all[r] = RecvFrame(fds_[r]);
      if (root == 0) {
        out = std::move(all);
      } else {
        for (int r = 0; r < nranks_; ++r)
          if (r != root) SendFrame(fds_[root], all[r]);
      }
    } else {
      SendFrame(fd_, payload);
      if (rank_ == root) {
        out.assign(nranks_, {});
        out[root] = payload;
        for (int r = 0; r < nranks_; ++r)
          if (r != root) out[r] = RecvFrame(fd_);
      }
    }
  }

  void ReduceToRoot(std::vector<uint8_t>& buf, int root,
                    const MergeFn& merge) override {
    // Ordered fold at root (the reference's non-commutative op,
    // TFIDF.c:324) — same construction as MpiComm.
    std::vector<std::vector<uint8_t>> all;
    GatherVariable(buf, root, all);
    if (rank_ == root) {
      for (int r = 0; r < (int)all.size(); ++r) {
        if (r == root) continue;
        merge(all[r], buf);
      }
    }
  }

  void Barrier() override {
    if (rank_ == 0) {
      for (int r = 1; r < nranks_; ++r) RecvFrame(fds_[r]);
      std::vector<uint8_t> token;
      for (int r = 1; r < nranks_; ++r) SendFrame(fds_[r], token);
    } else {
      SendFrame(fd_, {});
      RecvFrame(fd_);
    }
  }

 private:
  int rank_, nranks_;
  std::vector<int> fds_;
  int fd_;
};

}  // namespace

int RunProcessRanks(int nranks, const std::function<int(Comm&)>& body) {
  std::vector<int> hub_fds(nranks, -1);
  std::vector<pid_t> pids(nranks, -1);
  for (int r = 1; r < nranks; ++r) {
    int sv[2];
    if (::socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
      std::perror("socketpair");
      return 70;
    }
    pid_t pid = ::fork();
    if (pid < 0) {
      std::perror("fork");
      return 70;
    }
    if (pid == 0) {           // child = rank r
      ::close(sv[0]);
      for (int i = 1; i < r; ++i) ::close(hub_fds[i]);  // hub's earlier fds
      ProcessComm comm(r, nranks, {}, sv[1]);
      int rc = body(comm);
      ::close(sv[1]);
      ::_exit(rc & 0xFF);
    }
    ::close(sv[1]);
    hub_fds[r] = sv[0];
    pids[r] = pid;
  }
  ProcessComm comm(0, nranks, hub_fds, -1);
  int rc = body(comm);
  for (int r = 1; r < nranks; ++r) {
    ::close(hub_fds[r]);
    int status = 0;
    ::waitpid(pids[r], &status, 0);
    if (!WIFEXITED(status) || WEXITSTATUS(status) != 0) rc = rc ? rc : 71;
  }
  return rc;
}

}  // namespace tfidf

#ifdef TFIDF_HAVE_MPI
#include <mpi.h>

namespace tfidf {
namespace {

class MpiComm : public Comm {
 public:
  int rank() const override {
    int r;
    MPI_Comm_rank(MPI_COMM_WORLD, &r);
    return r;
  }
  int size() const override {
    int s;
    MPI_Comm_size(MPI_COMM_WORLD, &s);
    return s;
  }

  void Broadcast(std::vector<uint8_t>& buf, int root) override {
    // Two-phase: size then payload — replaces the reference's derived
    // datatype (TFIDF.c:78-89) with an explicit length prefix, fixing
    // its truncated-extent bug (SURVEY §2.5-2) by construction.
    uint64_t n = buf.size();
    MPI_Bcast(&n, 1, MPI_UINT64_T, root, MPI_COMM_WORLD);
    buf.resize(n);
    if (n) MPI_Bcast(buf.data(), (int)n, MPI_BYTE, root, MPI_COMM_WORLD);
  }

  void ReduceToRoot(std::vector<uint8_t>& buf, int root,
                    const MergeFn& merge) override {
    // Ordered fold at root via the gather primitive: the reference's op
    // is non-commutative (TFIDF.c:324), so a tree reduction with
    // arbitrary pairing would change insert-order tie-breaking.
    std::vector<std::vector<uint8_t>> all;
    GatherVariable(buf, root, all);
    if (rank() == root) {
      for (int r = 0; r < (int)all.size(); ++r) {
        if (r == root) continue;
        merge(all[r], buf);
      }
    }
  }

  void GatherVariable(const std::vector<uint8_t>& payload, int root,
                      std::vector<std::vector<uint8_t>>& out) override {
    const int nranks = size(), me = rank();
    if (me == root) {
      out.assign(nranks, {});
      out[root] = payload;
      for (int r = 0; r < nranks; ++r) {
        if (r == root) continue;
        uint64_t n;
        MPI_Recv(&n, 1, MPI_UINT64_T, r, 0, MPI_COMM_WORLD, MPI_STATUS_IGNORE);
        out[r].resize(n);
        if (n)
          MPI_Recv(out[r].data(), (int)n, MPI_BYTE, r, 1, MPI_COMM_WORLD,
                   MPI_STATUS_IGNORE);
      }
    } else {
      uint64_t n = payload.size();
      MPI_Send(&n, 1, MPI_UINT64_T, root, 0, MPI_COMM_WORLD);
      if (n)
        MPI_Send(const_cast<uint8_t*>(payload.data()), (int)n, MPI_BYTE, root,
                 1, MPI_COMM_WORLD);
    }
  }

  void Barrier() override { MPI_Barrier(MPI_COMM_WORLD); }
};

}  // namespace

Comm* CreateMpiComm() { return new MpiComm(); }

}  // namespace tfidf
#endif  // TFIDF_HAVE_MPI
