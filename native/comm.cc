// Thread-backed (and optional MPI-backed) implementations of the Comm
// interface declared in comm.h.

#include "comm.h"

#include <condition_variable>
#include <mutex>
#include <thread>

namespace tfidf {
namespace {

// Shared state for one thread-“cluster”. A generation-counted barrier
// plus a mailbox table; every collective is fenced by barriers on both
// sides, so one mailbox slot per rank suffices.
struct ThreadWorld {
  explicit ThreadWorld(int n) : nranks(n), mailbox(n) {}

  const int nranks;
  std::mutex mu;
  std::condition_variable cv;
  int arrived = 0;
  uint64_t generation = 0;
  std::vector<std::vector<uint8_t>> mailbox;

  void Barrier() {
    std::unique_lock<std::mutex> lock(mu);
    const uint64_t gen = generation;
    if (++arrived == nranks) {
      arrived = 0;
      ++generation;
      cv.notify_all();
    } else {
      cv.wait(lock, [&] { return generation != gen; });
    }
  }
};

class ThreadComm : public Comm {
 public:
  ThreadComm(ThreadWorld* world, int rank) : world_(world), rank_(rank) {}

  int rank() const override { return rank_; }
  int size() const override { return world_->nranks; }

  void Broadcast(std::vector<uint8_t>& buf, int root) override {
    if (rank_ == root) world_->mailbox[root] = buf;
    world_->Barrier();  // publish
    if (rank_ != root) buf = world_->mailbox[root];
    world_->Barrier();  // consume before root reuses the slot
  }

  void ReduceToRoot(std::vector<uint8_t>& buf, int root,
                    const MergeFn& merge) override {
    world_->mailbox[rank_] = buf;
    world_->Barrier();  // all contributions published
    if (rank_ == root) {
      for (int r = 0; r < world_->nranks; ++r) {
        if (r == root) continue;
        merge(world_->mailbox[r], buf);  // deterministic rank order
      }
    }
    world_->Barrier();  // merges done before slots are reused
  }

  void GatherVariable(const std::vector<uint8_t>& payload, int root,
                      std::vector<std::vector<uint8_t>>& out) override {
    world_->mailbox[rank_] = payload;
    world_->Barrier();
    if (rank_ == root) out = world_->mailbox;
    world_->Barrier();
  }

  void Barrier() override { world_->Barrier(); }

 private:
  ThreadWorld* world_;
  int rank_;
};

}  // namespace

void RunThreadRanks(int nranks, const std::function<void(Comm&)>& body) {
  ThreadWorld world(nranks);
  std::vector<std::thread> threads;
  threads.reserve(nranks);
  for (int r = 0; r < nranks; ++r) {
    threads.emplace_back([&world, r, &body] {
      ThreadComm comm(&world, r);
      body(comm);
    });
  }
  for (auto& t : threads) t.join();
}

}  // namespace tfidf

#ifdef TFIDF_HAVE_MPI
#include <mpi.h>

namespace tfidf {
namespace {

class MpiComm : public Comm {
 public:
  int rank() const override {
    int r;
    MPI_Comm_rank(MPI_COMM_WORLD, &r);
    return r;
  }
  int size() const override {
    int s;
    MPI_Comm_size(MPI_COMM_WORLD, &s);
    return s;
  }

  void Broadcast(std::vector<uint8_t>& buf, int root) override {
    // Two-phase: size then payload — replaces the reference's derived
    // datatype (TFIDF.c:78-89) with an explicit length prefix, fixing
    // its truncated-extent bug (SURVEY §2.5-2) by construction.
    uint64_t n = buf.size();
    MPI_Bcast(&n, 1, MPI_UINT64_T, root, MPI_COMM_WORLD);
    buf.resize(n);
    if (n) MPI_Bcast(buf.data(), (int)n, MPI_BYTE, root, MPI_COMM_WORLD);
  }

  void ReduceToRoot(std::vector<uint8_t>& buf, int root,
                    const MergeFn& merge) override {
    // Ordered fold at root via the gather primitive: the reference's op
    // is non-commutative (TFIDF.c:324), so a tree reduction with
    // arbitrary pairing would change insert-order tie-breaking.
    std::vector<std::vector<uint8_t>> all;
    GatherVariable(buf, root, all);
    if (rank() == root) {
      for (int r = 0; r < (int)all.size(); ++r) {
        if (r == root) continue;
        merge(all[r], buf);
      }
    }
  }

  void GatherVariable(const std::vector<uint8_t>& payload, int root,
                      std::vector<std::vector<uint8_t>>& out) override {
    const int nranks = size(), me = rank();
    if (me == root) {
      out.assign(nranks, {});
      out[root] = payload;
      for (int r = 0; r < nranks; ++r) {
        if (r == root) continue;
        uint64_t n;
        MPI_Recv(&n, 1, MPI_UINT64_T, r, 0, MPI_COMM_WORLD, MPI_STATUS_IGNORE);
        out[r].resize(n);
        if (n)
          MPI_Recv(out[r].data(), (int)n, MPI_BYTE, r, 1, MPI_COMM_WORLD,
                   MPI_STATUS_IGNORE);
      }
    } else {
      uint64_t n = payload.size();
      MPI_Send(&n, 1, MPI_UINT64_T, root, 0, MPI_COMM_WORLD);
      if (n)
        MPI_Send(const_cast<uint8_t*>(payload.data()), (int)n, MPI_BYTE, root,
                 1, MPI_COMM_WORLD);
    }
  }

  void Barrier() override { MPI_Barrier(MPI_COMM_WORLD); }
};

}  // namespace

Comm* CreateMpiComm() { return new MpiComm(); }

}  // namespace tfidf
#endif  // TFIDF_HAVE_MPI
