// loader — native parallel corpus loader (read + tokenize + hash + pack).
//
// The reference streams each document token-at-a-time through fscanf on
// one MPI rank (TFIDF.c:134-147, two passes per file: docSize count then
// re-scan). This loader is the framework's host data-loader equivalent,
// built for feeding a TPU: a std::thread pool with an atomic work queue
// reads doc files into an in-memory arena, counts tokens (pass 1), then
// tokenizes+FNV-hashes straight into the caller's padded [D, L] int32
// batch (pass 2) — the same two-pass shape as the reference, but
// per-file work-stolen across threads and with zero Python in the loop.
//
// C ABI (ctypes from tfidf_tpu/io/fast_tokenizer.py):
//   loader_open(paths, n_docs, n_threads) -> handle   (reads + counts)
//   loader_token_count(h, i) / loader_max_count(h) / loader_error(h)
//   loader_fill(h, seed, vocab, trunc, ids, stride, lengths, n_threads)
//   loader_close(h)
//
// Tokenize/hash semantics are contract-identical to fast_tokenizer.cc
// (fixed ASCII isspace, FNV-1a64 ^ seed, xor-fold, % vocab) — pinned by
// tests/test_native.py against the Python path.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "tokenize_common.h"

namespace {

using tfidf::IsSpace;

struct Loader {
  std::vector<std::string> paths;
  std::vector<std::string> docs;     // file contents (arena)
  std::vector<int64_t> counts;       // tokens per doc
  std::atomic<int64_t> failed{-1};   // first doc index that failed to read
};

int64_t CountTokens(const uint8_t* data, int64_t len) {
  int64_t n = 0, i = 0;
  while (i < len) {
    while (i < len && IsSpace(data[i])) ++i;
    if (i < len) ++n;
    while (i < len && !IsSpace(data[i])) ++i;
  }
  return n;
}

bool ReadFile(const std::string& path, std::string* out) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz < 0) { std::fclose(f); return false; }
  std::fseek(f, 0, SEEK_SET);
  out->resize((size_t)sz);
  size_t got = sz ? std::fread(&(*out)[0], 1, (size_t)sz, f) : 0;
  std::fclose(f);
  return got == (size_t)sz;
}

using tfidf::ParallelFor;  // shared with rerank.cc (tokenize_common.h)

// Tokenize+hash every loaded doc into the caller's padded [D, stride]
// batch of T-typed ids (shared contract: tokenize_common.h).
template <typename T>
void FillImpl(Loader* L, uint64_t seed, int64_t vocab_size,
              int64_t truncate_at, T* out_ids, int64_t stride,
              int32_t* out_lengths, int n_threads) {
  ParallelFor((int64_t)L->docs.size(), n_threads, [=](int64_t d) {
    int64_t n = tfidf::TokenizeHashInto(
        reinterpret_cast<const uint8_t*>(L->docs[d].data()),
        (int64_t)L->docs[d].size(), seed, vocab_size, truncate_at,
        out_ids + d * stride, stride);
    out_lengths[d] = (int32_t)n;
  });
}

}  // namespace

extern "C" {

// paths: n_docs NUL-terminated strings, back to back. Reads every file
// in parallel; counts tokens per file only when want_counts != 0 — the
// count is a whole extra scan of every byte, and callers that pin the
// batch shape (fixed_len chunked ingest) never read it. Returns a
// handle (never null); check loader_error() before trusting the data.
void* loader_open2(const char* paths, int64_t n_docs, int n_threads,
                   int want_counts) {
  Loader* L = new Loader;
  L->paths.reserve(n_docs);
  const char* p = paths;
  for (int64_t i = 0; i < n_docs; ++i) {
    L->paths.emplace_back(p);
    p += L->paths.back().size() + 1;
  }
  L->docs.resize(n_docs);
  L->counts.assign(n_docs, 0);
  ParallelFor(n_docs, n_threads, [L, want_counts](int64_t i) {
    if (!ReadFile(L->paths[i], &L->docs[i])) {
      int64_t expect = -1;
      L->failed.compare_exchange_strong(expect, i);
      return;
    }
    if (want_counts)
      L->counts[i] = CountTokens(
          reinterpret_cast<const uint8_t*>(L->docs[i].data()),
          (int64_t)L->docs[i].size());
  });
  return L;
}

void* loader_open(const char* paths, int64_t n_docs, int n_threads) {
  return loader_open2(paths, n_docs, n_threads, /*want_counts=*/1);
}

// Read-only views for sibling engines (rerank.cc): doc count and the
// raw bytes of doc d. The handle must outlive every returned pointer.
int64_t loader_doc_count(void* handle) {
  return (int64_t)static_cast<Loader*>(handle)->docs.size();
}

const char* loader_doc_data(void* handle, int64_t d, int64_t* len) {
  const std::string& s = static_cast<Loader*>(handle)->docs[d];
  *len = (int64_t)s.size();
  return s.data();
}

// Index of the first unreadable file, or -1. (The reference hard-exits
// on open failure, TFIDF.c:137; Python raises FileNotFoundError.)
int64_t loader_error(void* handle) {
  return static_cast<Loader*>(handle)->failed.load();
}

int64_t loader_token_count(void* handle, int64_t doc) {
  return static_cast<Loader*>(handle)->counts[doc];
}

int64_t loader_max_count(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  int64_t m = 0;
  for (int64_t c : L->counts) m = c > m ? c : m;
  return m;
}

// Tokenize+hash every doc into out_ids (row i at out_ids + i*stride;
// caller zero-fills for padding) and out_lengths. stride must be >=
// loader_max_count(); rows past n_docs are untouched (mesh padding).
void loader_fill(void* handle, uint64_t seed, int64_t vocab_size,
                 int64_t truncate_at, int32_t* out_ids, int64_t stride,
                 int32_t* out_lengths, int n_threads) {
  FillImpl(static_cast<Loader*>(handle), seed, vocab_size, truncate_at,
           out_ids, stride, out_lengths, n_threads);
}

// uint16 variant for vocab_size <= 65536: same ids, half the bytes on
// the host->device wire (the batch upcasts to int32 on device for free).
void loader_fill_u16(void* handle, uint64_t seed, int64_t vocab_size,
                     int64_t truncate_at, uint16_t* out_ids, int64_t stride,
                     int32_t* out_lengths, int n_threads) {
  FillImpl(static_cast<Loader*>(handle), seed, vocab_size, truncate_at,
           out_ids, stride, out_lengths, n_threads);
}

// Ragged (flat) variant: every doc's ids back to back with NO padding —
// the host->device wire for the resident ingest path, where zero-fill
// padding averaged ~25% of the bytes on the measured corpus and the
// tunneled link is the pipeline floor. Each doc is truncated to
// max_per_doc tokens; out must hold n_docs * max_per_doc ids (worst
// case). Returns total ids written. Serial by design: each doc's
// offset depends on every prior doc's count, and the deployment host
// has a single core anyway (a count prepass + parallel fill would cost
// the very scan loader_open2(want_counts=0) exists to skip).
// ``align``: each doc starts at a multiple of this many ids (zero
// fill between docs). An aligned layout lets the device rebuild the
// padded batch by gathering [L/align]-granule rows instead of per-id
// scalars — the per-element gather measured 67.5 ms/chunk at the
// bench shape (tools/trace_capture.py, round 5) for ~4% more wire
// bytes at align=16. align <= 1 is the legacy back-to-back layout.
int64_t loader_fill_flat_u16(void* handle, uint64_t seed,
                             int64_t vocab_size, int64_t truncate_at,
                             int64_t max_per_doc, uint16_t* out,
                             int32_t* out_lengths, int64_t align) {
  Loader* L = static_cast<Loader*>(handle);
  int64_t pos = 0;
  for (size_t d = 0; d < L->docs.size(); ++d) {
    int64_t n = tfidf::TokenizeHashInto(
        reinterpret_cast<const uint8_t*>(L->docs[d].data()),
        (int64_t)L->docs[d].size(), seed, vocab_size, truncate_at,
        out + pos, max_per_doc);
    out_lengths[d] = (int32_t)n;
    pos += n;
    if (align > 1) {
      int64_t pad = (align - pos % align) % align;
      std::memset(out + pos, 0, (size_t)pad * sizeof(uint16_t));
      pos += pad;
    }
  }
  return pos;
}

// Capacity-aware flat fill: identical ragged layout, but the caller
// hands the buffer's full staging CAPACITY (in ids — the ingest
// packers pass the bucket-rounded chunk capacity) and the tail
// [total, cap) is zero-filled HERE, so the wire buffer leaves native
// ragged AND ship-ready: no Python re-pad/memset pass, and the old
// flow's np.pad copy (when the bucket pad outgrew the buffer) cannot
// happen by construction.
int64_t loader_fill_flat_u16_v2(void* handle, uint64_t seed,
                                int64_t vocab_size, int64_t truncate_at,
                                int64_t max_per_doc, uint16_t* out,
                                int64_t cap, int32_t* out_lengths,
                                int64_t align) {
  int64_t total = loader_fill_flat_u16(handle, seed, vocab_size,
                                       truncate_at, max_per_doc, out,
                                       out_lengths, align);
  if (total < cap)
    std::memset(out + total, 0,
                (size_t)(cap - total) * sizeof(uint16_t));
  return total;
}

// Threaded flat fill (round 14 — the reference's "extra" variant is
// five OpenMP pragmas over exactly this per-doc loop,
// TFIDF_extra.c:69-302; done properly here on the shared ParallelFor
// pool): a parallel capped token-count prepass fixes every doc's
// aligned offset, then the tokenize+hash fill runs per-doc
// work-stolen across threads, each doc writing (and zero-padding) its
// own disjoint slice. Bit-identical output to the serial v2 fill —
// offsets depend only on the capped counts, which the prepass
// computes exactly (pinned by tests/test_native.py). The serial fills
// above remain for single-core hosts and stale-.so fallback.
int64_t loader_fill_flat_u16_v3(void* handle, uint64_t seed,
                                int64_t vocab_size, int64_t truncate_at,
                                int64_t max_per_doc, uint16_t* out,
                                int64_t cap, int32_t* out_lengths,
                                int64_t align, int n_threads) {
  Loader* L = static_cast<Loader*>(handle);
  int64_t n_docs = (int64_t)L->docs.size();
  std::vector<int64_t> offs(n_docs + 1, 0);
  ParallelFor(n_docs, n_threads, [&](int64_t d) {
    // Capped count: exactly the tokens TokenizeHashInto will write.
    int64_t n = tfidf::ForEachToken(
        reinterpret_cast<const uint8_t*>(L->docs[d].data()),
        (int64_t)L->docs[d].size(), /*truncate_at=*/0, max_per_doc,
        [](const uint8_t*, int64_t) {});
    offs[d + 1] = n;  // counts first; prefixed below
  });
  for (int64_t d = 0; d < n_docs; ++d) {
    int64_t n = offs[d + 1];
    int64_t padded = align > 1 ? (n + align - 1) / align * align : n;
    offs[d + 1] = offs[d] + padded;
  }
  int64_t total = offs[n_docs];
  ParallelFor(n_docs, n_threads, [&](int64_t d) {
    int64_t n = tfidf::TokenizeHashInto(
        reinterpret_cast<const uint8_t*>(L->docs[d].data()),
        (int64_t)L->docs[d].size(), seed, vocab_size, truncate_at,
        out + offs[d], max_per_doc);
    out_lengths[d] = (int32_t)n;
    int64_t pad = offs[d + 1] - offs[d] - n;
    if (pad > 0)
      std::memset(out + offs[d] + n, 0, (size_t)pad * sizeof(uint16_t));
  });
  if (total < cap)
    std::memset(out + total, 0,
                (size_t)(cap - total) * sizeof(uint16_t));
  return total;
}

// --- bytes wire (round 14): raw byte slab, zero host tokenize -------
//
// The slab layout contract (ops/device_tokenize.py docstring): doc d's
// raw bytes start at sum of ceil((blen_e + 1) / align) * align over
// e < d — at least one fill byte between docs — and every
// non-document byte is 0x20 (space), so the device tokenizer sees
// whitespace separators and can never merge adjacent documents or
// manufacture phantom tokens from fill.

// Total aligned slab bytes of the loaded docs — sizes the staging
// buffer (callers round up to the byte bucket for the compile cache).
int64_t loader_slab_bytes(void* handle, int64_t align) {
  Loader* L = static_cast<Loader*>(handle);
  int64_t a = align > 1 ? align : 1;
  int64_t total = 0;
  for (const std::string& s : L->docs)
    total += ((int64_t)s.size() + a) / a * a;
  return total;
}

// Byte-slab fill: one space memset over the whole capacity, then a
// parallel memcpy of each doc's raw bytes at its aligned offset. This
// IS the bytes wire's entire host pack — no tokenize, no hash, no id
// store; the per-token loop the reference parallelizes is gone from
// the host entirely. Returns total aligned bytes (<= cap).
int64_t loader_fill_slab(void* handle, uint8_t* out, int64_t cap,
                         int32_t* out_blens, int64_t align,
                         int n_threads) {
  Loader* L = static_cast<Loader*>(handle);
  int64_t n_docs = (int64_t)L->docs.size();
  int64_t a = align > 1 ? align : 1;
  std::vector<int64_t> offs(n_docs, 0);
  int64_t total = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    offs[d] = total;
    total += ((int64_t)L->docs[d].size() + a) / a * a;
  }
  if (total > cap) return -1;  // caller sized the buffer from
                               // loader_slab_bytes; cannot happen
  std::memset(out, 0x20, (size_t)cap);
  ParallelFor(n_docs, n_threads, [&](int64_t d) {
    const std::string& s = L->docs[d];
    if (!s.empty()) std::memcpy(out + offs[d], s.data(), s.size());
    out_blens[d] = (int32_t)s.size();
  });
  return total;
}

void loader_close(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
