// rerank — native exact-string re-rank of a hashed device top-k.
//
// The TPU path scores hash *buckets*; the north star asks for the
// reference's exact top-k terms (string-keyed tables, TFIDF.c:26-42).
// tfidf_tpu/rerank.py closes that gap with a host post-pass; round 2
// measured its pure-Python passes at 0.39x the CPU oracle — the one
// mode emitting the reference's actual words lost to the reference.
// This file is that post-pass as a native pipeline over the loader's
// in-memory arena (document bytes never enter Python):
//
//   pass 1 (parallel over docs): tokenize, hash each token, and count
//     exact occurrences of every word whose bucket made that doc's
//     device top-k margin (candidate words).
//   pass 2 (parallel over docs): exact document frequency of the global
//     candidate-word set, with per-doc dedup (the currDoc semantics,
//     TFIDF.c:171-188), via relaxed atomics on a read-only index.
//   pass 3 (parallel over docs): float64 TF-IDF in the reference's op
//     order (tf = count/docSize; idf = ln(N/df); score = tf*idf,
//     TFIDF.c:202,243), filter score > 0, sort by (-score, word),
//     keep k.
//
// Tokenize/hash semantics are the shared contract (tokenize_common.h);
// words are compared/stored after per-token truncation, matching
// whitespace_tokenize(data, truncate_at). Python-side bindings and the
// result decode live in tfidf_tpu/rerank.py; parity with the Python
// implementation is pinned by tests/test_rerank.py.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <string_view>
#include <vector>

#include "tokenize_common.h"

// Defined in loader.cc: borrow a read-only view of doc d's bytes. The
// loader handle owns the arena and must outlive the rerank call.
extern "C" int64_t loader_doc_count(void* handle);
extern "C" const char* loader_doc_data(void* handle, int64_t d,
                                       int64_t* len);

namespace {

using tfidf::ParallelFor;

// The string_view token adapters (ForEachTokenView / HashView /
// HashedTok) live in tokenize_common.h, shared with intern.cc's
// exact_emit — the single source of truth; no local copies.
using tfidf::ForEachTokenView;
using tfidf::HashView;
using tfidf::HashedTok;
using tfidf::HashedTokLess;

struct Cand {               // one unique word in one doc (32 bytes)
  uint64_t h;
  std::string_view w;       // view into the loader arena
  int32_t count;
  int32_t idx;              // global candidate index, -1 = non-candidate
};

// Open-addressed global candidate index: h-keyed linear probing with
// byte verification on hash hits. Grown before pass 2; read-only and
// therefore thread-safe during the parallel passes. unordered_map
// per-doc/per-token churn measured ~2x the whole mode's budget at
// margin 4, which is why this table and the sort+RLE grouping below
// replaced it.
struct GlobalIndex {
  std::vector<uint64_t> hs;
  std::vector<std::string_view> ws;
  std::vector<int64_t> idxs;
  size_t mask = 0, live = 0;

  void Rehash(size_t cap) {  // cap: power of two
    std::vector<uint64_t> oh = std::move(hs);
    std::vector<std::string_view> ow = std::move(ws);
    std::vector<int64_t> oi = std::move(idxs);
    hs.assign(cap, 0);
    ws.assign(cap, {});
    idxs.assign(cap, -1);
    mask = cap - 1;
    for (size_t s = 0; s < oh.size(); ++s)
      if (oi[s] >= 0) Place(oh[s], ow[s], oi[s]);
  }

  void Place(uint64_t h, std::string_view w, int64_t idx) {
    size_t s = (size_t)h & mask;
    while (idxs[s] >= 0) s = (s + 1) & mask;
    hs[s] = h;
    ws[s] = w;
    idxs[s] = idx;
  }

  // Insert-if-absent; returns the word's global index.
  int64_t Intern(uint64_t h, std::string_view w) {
    if ((live + 1) * 10 >= (mask + 1) * 7) Rehash((mask + 1) * 2);
    size_t s = (size_t)h & mask;
    while (idxs[s] >= 0) {
      if (hs[s] == h && ws[s] == w) return idxs[s];
      s = (s + 1) & mask;
    }
    hs[s] = h;
    ws[s] = w;
    idxs[s] = (int64_t)live;
    return (int64_t)live++;
  }

  // Read-only probe (thread-safe after construction).
  int64_t Find(uint64_t h, std::string_view w) const {
    size_t s = (size_t)h & mask;
    while (idxs[s] >= 0) {
      if (hs[s] == h && ws[s] == w) return idxs[s];
      s = (s + 1) & mask;
    }
    return -1;
  }
};

struct Entry {
  std::string_view word;
  double score;
};

struct RerankResult {
  std::vector<int32_t> per_doc_counts;  // emitted entries per doc
  std::vector<int64_t> offs, lens;      // word spans in blob, entry order
  std::vector<double> scores;           // entry order (doc-major)
  std::string blob;                     // concatenated word bytes
};

}  // namespace

extern "C" {

// Exact re-rank over the docs held by a loader handle. topk_ids is the
// row-major [n_docs, kprime] device margin selection for exactly those
// docs (bucket ids; negatives = padding). num_docs_idf drives the exact
// IDF (the corpus count — it may exceed n_docs when the caller filters
// rows, but DF is counted over the handle's docs, so pass the full
// corpus for both unless you know better). Returns a RerankResult*.
void* rerank_run(void* loader_handle, const int32_t* topk_ids,
                 int64_t kprime, int64_t num_docs_idf, uint64_t seed,
                 int64_t vocab_size, int64_t truncate_at,
                 int64_t max_tokens, int64_t k, int n_threads) {
  const int64_t n_docs = loader_doc_count(loader_handle);

  // Pass 1: tokenize + hash + sort + RLE ONCE per doc, caching every
  // unique (hash, bytes, count) — later passes never touch document
  // bytes again (the second full tokenize+sort measured ~a third of
  // the mode's budget). Candidate entries (bucket made the device
  // margin) are remembered by slot. Memory: 32 B per unique term per
  // doc (views into the loader arena), held across all three passes —
  // ~tens of MB at bench scale, ~GBs at 1M docs (the arena itself is
  // the same order).
  std::vector<std::vector<Cand>> uniq(n_docs);
  std::vector<std::vector<int32_t>> cand_slots(n_docs);
  std::vector<int64_t> doc_size(n_docs, 0);
  ParallelFor(n_docs, n_threads, [&](int64_t d) {
    std::vector<int32_t> buckets;
    buckets.reserve((size_t)kprime);
    for (int64_t j = 0; j < kprime; ++j) {
      int32_t b = topk_ids[d * kprime + j];
      if (b >= 0) buckets.push_back(b);
    }
    std::sort(buckets.begin(), buckets.end());
    int64_t len;
    const char* data = loader_doc_data(loader_handle, d, &len);
    std::vector<HashedTok> toks;
    doc_size[d] = ForEachTokenView(
        data, len, truncate_at, max_tokens,
        [&](std::string_view w) { toks.push_back({HashView(w, seed), w}); });
    std::sort(toks.begin(), toks.end(), HashedTokLess);
    for (size_t i = 0; i < toks.size();) {
      size_t j = i + 1;
      while (j < toks.size() && toks[j].h == toks[i].h &&
             toks[j].w == toks[i].w)
        ++j;
      uniq[d].push_back({toks[i].h, toks[i].w, (int32_t)(j - i), -1});
      int32_t b = (int32_t)tfidf::FoldToVocab(toks[i].h, vocab_size);
      if (std::binary_search(buckets.begin(), buckets.end(), b))
        cand_slots[d].push_back((int32_t)uniq[d].size() - 1);
      i = j;
    }
  });

  // Global candidate index (serial merge of the flagged slots).
  GlobalIndex gidx;
  gidx.Rehash(1 << 16);
  for (int64_t d = 0; d < n_docs; ++d)
    for (int32_t s : cand_slots[d]) {
      Cand& c = uniq[d][(size_t)s];
      c.idx = (int32_t)gidx.Intern(c.h, c.w);
    }

  // Pass 2: exact DF of the candidate set, one count per (word, doc).
  // Dedup is already encoded in the cached unique lists (the currDoc
  // semantics); the global index is read-only here, probed with
  // relaxed-atomic counts.
  std::unique_ptr<std::atomic<int32_t>[]> df(
      new std::atomic<int32_t>[gidx.live ? gidx.live : 1]);
  for (size_t i = 0; i < gidx.live; ++i) df[i].store(0);
  ParallelFor(n_docs, n_threads, [&](int64_t d) {
    for (const Cand& c : uniq[d]) {
      int64_t idx = c.idx >= 0 ? c.idx : gidx.Find(c.h, c.w);
      if (idx >= 0) df[idx].fetch_add(1, std::memory_order_relaxed);
    }
  });

  // Pass 3: exact float64 scoring, (-score, word) order, top-k.
  std::vector<std::vector<Entry>> picked(n_docs);
  ParallelFor(n_docs, n_threads, [&](int64_t d) {
    std::vector<Entry>& out = picked[d];
    out.reserve(cand_slots[d].size());
    for (int32_t s : cand_slots[d]) {
      const Cand& c = uniq[d][(size_t)s];
      int32_t dfw = df[c.idx].load(std::memory_order_relaxed);
      double tf = (double)c.count / (double)doc_size[d];
      double idf = std::log((double)num_docs_idf / (double)dfw);
      double ssc = tf * idf;
      if (ssc > 0.0) out.push_back({c.w, ssc});
    }
    std::sort(out.begin(), out.end(), [](const Entry& a, const Entry& b) {
      if (a.score != b.score) return a.score > b.score;
      return a.word < b.word;
    });
    if ((int64_t)out.size() > k) out.resize((size_t)k);
  });

  // Assemble the flat result (serial).
  RerankResult* res = new RerankResult;
  res->per_doc_counts.resize(n_docs);
  int64_t total = 0, bytes = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    res->per_doc_counts[d] = (int32_t)picked[d].size();
    total += (int64_t)picked[d].size();
    for (const Entry& e : picked[d]) bytes += (int64_t)e.word.size();
  }
  res->offs.reserve(total);
  res->lens.reserve(total);
  res->scores.reserve(total);
  res->blob.reserve(bytes);
  for (int64_t d = 0; d < n_docs; ++d)
    for (const Entry& e : picked[d]) {
      res->offs.push_back((int64_t)res->blob.size());
      res->lens.push_back((int64_t)e.word.size());
      res->scores.push_back(e.score);
      res->blob.append(e.word);
    }
  return res;
}

int64_t rerank_total(void* res) {
  return (int64_t)static_cast<RerankResult*>(res)->scores.size();
}

int64_t rerank_blob_bytes(void* res) {
  return (int64_t)static_cast<RerankResult*>(res)->blob.size();
}

// Bulk copy-out: per_doc_counts [n_docs], offs/lens/scores [total],
// blob [blob_bytes]. One ctypes call; Python slices the blob.
void rerank_fill(void* res_p, int32_t* per_doc_counts, int64_t* offs,
                 int64_t* lens, double* scores, char* blob) {
  RerankResult* res = static_cast<RerankResult*>(res_p);
  std::memcpy(per_doc_counts, res->per_doc_counts.data(),
              res->per_doc_counts.size() * sizeof(int32_t));
  std::memcpy(offs, res->offs.data(), res->offs.size() * sizeof(int64_t));
  std::memcpy(lens, res->lens.data(), res->lens.size() * sizeof(int64_t));
  std::memcpy(scores, res->scores.data(),
              res->scores.size() * sizeof(double));
  std::memcpy(blob, res->blob.data(), res->blob.size());
}

void rerank_free(void* res) { delete static_cast<RerankResult*>(res); }

}  // extern "C"
